"""Broker metrics: counters + gauges + fixed-bucket histograms.

Parity with the reference's counter families (apps/emqx/src/emqx_metrics.erl:
89-104: bytes/packets/messages/deliveries; emqx_stats.erl gauges). Names use
the reference's dotted style so the management API and Prometheus exporter
surface familiar series.

Two additions over the reference's flat counter tables:

- a fixed-bucket `Histogram` (count/sum/cumulative buckets, lock-safe,
  p50/p95/p99 accessors) for the hot-path flight recorder — ingest batch
  occupancy, device match latency, dispatch fan-out;
- an explicit metric-kind REGISTRY: every series name is declared once with
  its kind (counter | gauge | histogram), so the exporters render `# TYPE`
  lines from declarations instead of guessing from name substrings, and
  the MN checker (`python -m tools.analysis --checks metrics`) can
  statically reject typo'd series names.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import defaultdict
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"

# shared bucket ladders (upper bounds; +Inf is implicit)
LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)
SIZE_BUCKETS: Tuple[float, ...] = (
    1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096,
)
RATIO_BUCKETS: Tuple[float, ...] = (
    0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0,
)
FANOUT_BUCKETS: Tuple[float, ...] = (
    0, 1, 2, 4, 8, 16, 32, 64, 256, 1024, 4096,
)
# device->host transfer sizes (bytes; pow4 ladder from 4KB to 256MB —
# a dense 4096-row bitmap batch at 1M slots is ~512MB, compacted ~1MB)
READBACK_BUCKETS: Tuple[float, ...] = (
    4096, 16384, 65536, 262144, 1048576, 4194304,
    16777216, 67108864, 268435456,
)


@dataclass(frozen=True)
class MetricSpec:
    name: str
    kind: str  # COUNTER | GAUGE | HISTOGRAM
    help: str = ""
    # histogram-only: upper bucket bounds; None => LATENCY_BUCKETS
    buckets: Optional[Tuple[float, ...]] = None
    # histogram-only: "seconds" lets the StatsD exporter render timers
    unit: str = ""


_REGISTRY: Dict[str, MetricSpec] = {}


def declare(
    name: str,
    kind: str,
    help: str = "",
    buckets: Optional[Sequence[float]] = None,
    unit: str = "",
) -> MetricSpec:
    """Register a series name with its kind. Re-declaring with the same
    kind is a no-op; a conflicting kind is a programming error."""
    if kind not in (COUNTER, GAUGE, HISTOGRAM):
        raise ValueError(f"unknown metric kind {kind!r}")
    prev = _REGISTRY.get(name)
    if prev is not None:
        if prev.kind != kind:
            raise ValueError(
                f"metric {name!r} re-declared as {kind}, was {prev.kind}"
            )
        return prev
    s = MetricSpec(
        name=name,
        kind=kind,
        help=help,
        buckets=tuple(buckets) if buckets is not None else None,
        unit=unit,
    )
    _REGISTRY[name] = s
    return s


def spec(name: str) -> Optional[MetricSpec]:
    return _REGISTRY.get(name)


def kind_of(name: str) -> Optional[str]:
    s = _REGISTRY.get(name)
    return s.kind if s is not None else None


def registry() -> Dict[str, MetricSpec]:
    """Snapshot of every declared series (runtime mirror of the set the
    MN checker collects statically)."""
    return dict(_REGISTRY)


class Histogram:
    """Fixed-bucket histogram: counts per upper bound + sum + total count.

    Prometheus-shaped (cumulative `_bucket{le=...}` + `_sum`/`_count`),
    lock-safe (`observe` runs from executor threads on the device-dispatch
    path). Percentiles interpolate linearly inside the landing bucket —
    exact enough for p50/p95/p99 dashboards, never a per-sample store.
    """

    __slots__ = ("bounds", "_counts", "sum", "count", "_lock")

    def __init__(self, buckets: Sequence[float] = LATENCY_BUCKETS):
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.bounds = bounds  # immutable after construction
        self._counts = [0] * (len(bounds) + 1)  # guarded-by: _lock
        self.sum = 0.0  # guarded-by: _lock
        self.count = 0  # guarded-by: _lock
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[i] += 1
            self.sum += value
            self.count += 1

    def observe_many(self, values: Sequence[float]) -> None:
        """Batch observe under one lock acquisition (settle loops record
        thousands of per-message latencies per batch)."""
        if not len(values):
            return
        idxs = [bisect.bisect_left(self.bounds, v) for v in values]
        with self._lock:
            for i in idxs:
                self._counts[i] += 1
            self.sum += float(sum(values))
            self.count += len(values)

    def percentile(self, q: float) -> float:
        """q in [0, 1]. 0.0 when empty; the last finite bound when the
        quantile lands in the +Inf overflow bucket."""
        with self._lock:
            counts = list(self._counts)
            total = self.count
        if total == 0:
            return 0.0
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            prev_cum = cum
            cum += c
            if cum >= rank:
                if i >= len(self.bounds):  # +Inf bucket
                    return self.bounds[-1]
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i]
                frac = (rank - prev_cum) / c if c else 1.0
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        return self.bounds[-1]

    @property
    def p50(self) -> float:
        return self.percentile(0.50)

    @property
    def p95(self) -> float:
        return self.percentile(0.95)

    @property
    def p99(self) -> float:
        return self.percentile(0.99)

    def snapshot(self) -> Dict:
        """-> {"count", "sum", "buckets": [(le, cumulative_count), ...]}
        with a final (inf, count) entry — exactly the exposition shape."""
        with self._lock:
            counts = list(self._counts)
            total = self.count
            s = self.sum
        out: List[Tuple[float, int]] = []
        cum = 0
        for le, c in zip(self.bounds, counts):
            cum += c
            out.append((le, cum))
        out.append((float("inf"), total))
        return {"count": total, "sum": s, "buckets": out}


class Metrics:
    def __init__(self) -> None:
        self._counters: Dict[str, int] = defaultdict(int)  # guarded-by: _lock
        self._gauges: Dict[str, float] = {}  # guarded-by: _lock
        self._histograms: Dict[str, Histogram] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self.started_at = time.time()

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] += n

    def get(self, name: str) -> int:
        return self._counters.get(name, 0)

    def gauge_set(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def gauge(self, name: str) -> float:
        with self._lock:
            return self._gauges.get(name, 0.0)

    # -- histograms --------------------------------------------------------
    def _histogram(self, name: str) -> Histogram:
        # double-checked locking: the dict read is GIL-atomic and the
        # slow path re-checks under _lock
        h = self._histograms.get(name)  # lint: disable=LK001
        if h is None:
            with self._lock:
                h = self._histograms.get(name)
                if h is None:
                    s = _REGISTRY.get(name)
                    h = Histogram(
                        s.buckets
                        if s is not None and s.buckets is not None
                        else LATENCY_BUCKETS
                    )
                    self._histograms[name] = h
        return h

    def observe(self, name: str, value: float) -> None:
        self._histogram(name).observe(value)

    def observe_many(self, name: str, values: Sequence[float]) -> None:
        self._histogram(name).observe_many(values)

    def histogram(self, name: str) -> Optional[Histogram]:
        with self._lock:
            return self._histograms.get(name)

    def histograms(self) -> Dict[str, Dict]:
        """name -> Histogram.snapshot() for every recorded histogram."""
        with self._lock:
            items = list(self._histograms.items())
        return {name: h.snapshot() for name, h in items}

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self._counters)
            out.update(self._gauges)
        out["uptime_seconds"] = time.time() - self.started_at
        return out


default_metrics = Metrics()


# -- series declarations ---------------------------------------------------
# Every name passed to Metrics.inc/gauge_set/observe anywhere in emqx_tpu/
# must be declared here (enforced by the MN checker in tools/analysis, run
# as a tier-1 test). Grouped by subsystem.

# packets / messages (emqx_metrics.erl families)
declare("packets.sent", COUNTER, "MQTT packets written to clients")
declare("packets.received", COUNTER, "MQTT packets read from clients")
declare("messages.received", COUNTER, "messages entering dispatch")
declare("messages.delivered", COUNTER, "deliveries handed to subscribers")
declare("messages.dropped", COUNTER, "messages dropped before dispatch")
declare("messages.dropped.no_subscribers", COUNTER)
declare("messages.dropped.not_authorized", COUNTER)
declare("messages.dispatch_error", COUNTER)
declare("messages.routed.device", COUNTER,
        "batch rows routed by the device kernel")
declare("messages.routed.device_fallback", COUNTER,
        "batch rows the device flagged; routed by the CPU trie")
declare("messages.forward.failed", COUNTER)
declare("delivery.errors", COUNTER)

# admission / overload
declare("limiter.refused.connection", COUNTER)
declare("limiter.dropped.message_routing", COUNTER)
declare("olp.refused", COUNTER)
declare("olp.lag_ms", GAUGE,
        "last sampled event-loop lag (the Olp overload signal)")
declare("olp.trips", COUNTER,
        "overload trips: lag crossed the watermark from a calm state")
declare("node.drained", COUNTER)

# -- fault injection + graceful degradation (observe/faults.py,
# broker/degrade.py; docs/robustness.md) ----------------------------------
declare("faults.injected", COUNTER,
        "fault-site fires across every armed rule (soak audit trail)")
declare("degrade.state.device", GAUGE,
        "device-path breaker state: 0 closed, 1 half-open, 2 open "
        "(open = batches served by the CPU trie)")
declare("degrade.state.cluster_send", GAUGE,
        "cluster-send breaker state (most recent transition across "
        "destinations): 0 closed, 1 half-open, 2 open")
declare("degrade.trips.device", COUNTER,
        "device-path breaker closed -> open transitions")
declare("degrade.trips.cluster_send", COUNTER,
        "cluster-send breaker closed -> open transitions (any dest)")
declare("degrade.probe.ok", COUNTER,
        "half-open probes that succeeded (recovery evidence)")
declare("degrade.probe.fail", COUNTER,
        "half-open probes that failed (dwell restarted)")
declare("degrade.retries", COUNTER,
        "bounded backoff retry attempts before degrading a batch")
declare("degrade.fallback.batches", COUNTER,
        "whole batches served by the CPU trie because the device path "
        "failed or its breaker was open")
declare("ingest.shed", COUNTER,
        "enqueues refused at the ingest gate (olp overloaded or device "
        "breaker open past the queue bound) — backpressure, not loss")
# SLO-driven adaptive batching (broker/slo.py; docs/robustness.md) -------
declare("slo.window_us", GAUGE,
        "current adaptive ingest window (microseconds)")
declare("slo.ladder.rung", GAUGE,
        "backpressure ladder rung: 0 normal, 1 widen, 2 defer, 3 shed")
declare("slo.p99.observed_ms", GAUGE,
        "enqueue->settle p99 over the last SLO evaluation window")
declare("slo.p99.target_ms", GAUGE,
        "configured p99 target the controller holds")
declare("slo.eval.windows", COUNTER,
        "SLO controller evaluation windows closed")
declare("slo.violations", COUNTER,
        "evaluation windows whose observed p99 missed the target")
declare("slo.adjustments", COUNTER,
        "window-size changes the controller applied")
declare("slo.deferrals", COUNTER,
        "launches the low-priority lane sat out on the defer rung")
declare("slo.shed", COUNTER,
        "enqueues refused by the graded shed rung (subset of ingest.shed)")
declare("retained.storm.deferred", COUNTER,
        "storm fuses/flushes deferred by the SLO ladder")
declare("router.sync.rollback", COUNTER,
        "dirty prepares that failed or tore and rolled back to the "
        "last good epoch snapshot")
declare("cluster.send.retries", COUNTER,
        "cluster send attempts retried after a transport failure")
declare("cluster.send.dead_letter", COUNTER,
        "cluster sends given up after deadline/retry budget (the "
        "bounded dead-letter count)")

# worker fabric (transport/workers.py)
declare("fabric.sess.crash_parked", COUNTER)
declare("fabric.sess.resumes", COUNTER)
declare("fabric.sess.takeovers", COUNTER)
declare("fabric.sess.decode_errors", COUNTER)
declare("fabric.flush.errors", COUNTER)
declare("fabric.parked.dropped", COUNTER)
declare("fabric.parked.replayed", COUNTER)
declare("fabric.puback.timeouts", COUNTER)
declare("fabric.raw.records", COUNTER)
declare("fabric.link.lost", COUNTER)
declare("fabric.link.reconnected", COUNTER)
declare("fabric.worker.crash_loop", COUNTER)
declare("fabric.worker.respawns", COUNTER)

# -- slab protocol plane (transport/fabric.py slab codec, zero-copy
# ingest, batched delivery/resend serialization; docs/protocol_plane.md)
declare("fabric.slab.pub.frames", COUNTER,
        "T_PUBB_S frames unpacked via the vectorized slab codec")
declare("fabric.slab.pub.records", COUNTER,
        "publish records recovered by slab header scans (no per-record "
        "struct.unpack, no tuple materialization)")
declare("fabric.slab.dlv.frames", COUNTER,
        "T_DLV_S delivery frames packed from once-serialized regions")
declare("fabric.slab.dlv.records", COUNTER,
        "delivery records packed via the slab codec (one per "
        "(message, worker) — fan-out stays worker-side)")
declare("ingest.zerocopy.records", COUNTER,
        "messages entering ingest as slab-backed views: topic bytes "
        "feed the tokenizer straight from the fabric read buffer")
declare("ingest.zerocopy.deferred.bytes", COUNTER,
        "topic+payload bytes whose str-decode/copy was deferred at "
        "ingest (paid later only if a consumer materializes)")
declare("dispatch.serialize.batches", COUNTER,
        "batched PUBLISH serialization passes (one slab build for a "
        "whole resend/delivery batch)")
declare("dispatch.serialize.frames", COUNTER,
        "outbound PUBLISH frames emitted by the slab serializer / "
        "split-frame fan-out (serialize once, patch the packet id)")
declare("dispatch.serialize.bytes", COUNTER,
        "bytes serialized by the batched slab passes")

# cluster
declare("cluster.nodedown.routes_purged", COUNTER)
declare("cluster.retain.bootstrap_failed", COUNTER)
declare("cluster.retain.dump_truncated", COUNTER)

# gauges (emqx_stats.erl analogs + monitor extras)
declare("connections.count", GAUGE)
declare("subscriptions.count", GAUGE)
declare("topics.count", GAUGE)
declare("retained.count", GAUGE)
declare("delayed.count", GAUGE)
declare("sessions.restored", GAUGE)
declare("cpu.usage", GAUGE)
declare("mem.usage", GAUGE)
declare("tasks.count", GAUGE)
declare("uptime_seconds", GAUGE)

# -- hot-path flight recorder (ingest -> matcher -> dispatch) --------------
declare("ingest.batch.size", HISTOGRAM,
        "messages per launched ingest batch", buckets=SIZE_BUCKETS)
declare("ingest.batch.occupancy", HISTOGRAM,
        "launched batch size / max_batch", buckets=RATIO_BUCKETS)
declare("ingest.window.wait.seconds", HISTOGRAM,
        "time the adaptive batch window was held open",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("ingest.settle.seconds", HISTOGRAM,
        "per-message enqueue -> settle (PUBACK-visible) latency",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("ingest.pipeline.depth", GAUGE,
        "device dispatches in flight after the last launch")
declare("ingest.device.idle.seconds", HISTOGRAM,
        "gap between the pipeline's device side draining and the next "
        "launch (the wall the idle partial-batch launch rule closes)",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("ingest.lane.depth.control", GAUGE,
        "pending control-lane messages (QoS2 flow / $SYS) at launch")
declare("ingest.lane.depth.normal", GAUGE,
        "pending normal-lane messages at launch")
declare("ingest.lane.depth.low", GAUGE,
        "pending low-lane messages (QoS0 firehose / tagged) at launch")
declare("ingest.lane.settle.seconds.control", HISTOGRAM,
        "control-lane enqueue->settle latency (the bounded-tail gate)",
        unit="seconds")
declare("ingest.lane.settle.seconds.normal", HISTOGRAM,
        "normal-lane enqueue->settle latency", unit="seconds")
declare("ingest.lane.settle.seconds.low", HISTOGRAM,
        "low-lane enqueue->settle latency (defer-eligible)",
        unit="seconds")
declare("ingest.lane.starvation.breaks", COUNTER,
        "launches that reserved low-lane slots past the starvation bound")
declare("ingest.launch.errors", COUNTER,
        "batch launches that raised before reaching the device")
declare("ingest.dispatch.errors", COUNTER,
        "batch dispatches that raised at settle time")

declare("matcher.rows", COUNTER, "topic rows offered to TpuMatcher")
declare("matcher.batch.size", HISTOGRAM, buckets=SIZE_BUCKETS)
declare("matcher.device.seconds", HISTOGRAM,
        "TpuMatcher device match wall time (launch + readback)",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("matcher.sync.seconds", HISTOGRAM,
        "DeviceDeltaSync upload time (full or delta)",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("matcher.fallback.rows", COUNTER,
        "rows flagged to the CPU trie (any cause)")
declare("matcher.fallback.rows.too_deep", COUNTER,
        "rows whose topic exceeds MatcherConfig.max_levels")
declare("matcher.fallback.rows.frontier_overflow", COUNTER,
        "rows whose NFA frontier overflowed MatcherConfig.frontier")
declare("matcher.fallback.rows.match_overflow", COUNTER,
        "rows with more matches than MatcherConfig.max_matches")
declare("matcher.fallback.rows.too_long", COUNTER,
        "rows whose topic exceeds MatcherConfig.max_bytes")

declare("router.batch.size", HISTOGRAM,
        "topic rows per serving-path device batch", buckets=SIZE_BUCKETS)
declare("router.device.seconds", HISTOGRAM,
        "serving-path route_step wall time (launch + readback)",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("router.sync.seconds", HISTOGRAM,
        "serving-path table snapshot + delta upload time",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("router.sync.skipped", COUNTER,
        "prepares that skipped pack/delta-sync entirely (every source "
        "table's generation counter unchanged — the steady state)")
declare("router.prepare.dirty", COUNTER,
        "prepares that re-snapshotted at least one table (churn since "
        "the last batch)")

# segmented update path (ops/segments.py, docs/update_path.md)
declare("router.segment.hot.fill", GAUGE,
        "live entries in the shape-index hot segment (subscribes since "
        "the last compaction)")
declare("router.segment.hot.capacity", GAUGE,
        "hot-segment slot capacity (pow2; grows by doubling, re-uploads "
        "alone via the per-array resync marker)")
declare("router.segment.tombstones", GAUGE,
        "tombstoned packed-table slots awaiting compaction (unsubscribed "
        "entries masked out of the match)")
declare("router.compact.runs", COUNTER,
        "background segment-compaction cycles applied (hot segment "
        "merged into a rebuilt packed table off the critical path)")
declare("router.compact.aborted", COUNTER,
        "compaction cycles discarded (a structural rebuild raced the "
        "background build; retried next interval)")
declare("router.compact.merged", COUNTER,
        "hot-segment entries merged into the packed table by compaction")
declare("router.compact.seconds", HISTOGRAM,
        "wall seconds per compaction cycle (capture + executor build + "
        "pre-upload + journal-replay apply)",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("router.compact.lag.seconds", GAUGE,
        "seconds the compaction trigger has been pending (0 when the "
        "hot segment is under threshold; sustained growth means "
        "compaction cannot keep up with churn)")

# sparse (CSR) subscriber table (ops/csr_table.py, router.sub_table
# policy; docs/serving_pipeline.md "subscriber-table memory budget")
declare("router.sparse.flips", COUNTER,
        "subscriber-table representation flips served (dense bitmap "
        "matrix <-> CSR slot lists; auto mode flips at most once)")
declare("router.sparse.overflow.rows", COUNTER,
        "sparse-path rows whose fan-out exceeded the Kslot/gather "
        "window and rebuilt their recipient set from the host table")
declare("router.sparse.bytes", GAUGE,
        "device footprint of the CSR subscriber table (slot column + "
        "region lanes + hot segment) — the sub_table_bytes number")
declare("router.sparse.fill", GAUGE,
        "live subscriptions in the CSR table")
declare("router.sparse.tombstones", GAUGE,
        "tombstoned CSR entries (packed column + hot) awaiting "
        "compaction")
declare("router.sparse.hot.fill", GAUGE,
        "live entries in the CSR hot segment (subscribes since the "
        "last compaction)")

# scale-out sharded serving (parallel/mesh.py dist_fused_step,
# cluster/route_sync.ShardOwnership, docs/scale_out.md)
declare("mesh.shard.count", GAUGE,
        "device shards in the local serving mesh (dp x tp product; 0 "
        "when SPMD serving is off)")
declare("mesh.shard.fill", GAUGE,
        "max per-tp-shard subscriber-lane occupancy (nonzero words / "
        "words in the fullest lane slice; sustained skew vs the min "
        "means one chip carries the fan-out wall)")
declare("mesh.shard.scatter.launches", COUNTER,
        "O(delta) scatter launches that landed on SHARDED mirrors "
        "(churn reaching the mesh without a full table re-upload)")
declare("mesh.shard.compact.runs", COUNTER,
        "background compaction cycles whose rebuilt tables pre-uploaded "
        "straight into the sharded layout (placement hook present)")
declare("mesh.shard.rebalance", COUNTER,
        "shard ownership moves after a node loss (rendezvous re-own; "
        "each move is one slice adopting a survivor)")
declare("mesh.shard.reroutes", COUNTER,
        "publish forwards rerouted from a dead shard owner to its "
        "rendezvous successor (the stall the re-own ladder removes)")

# -- device-resident session store (broker/session_store.py,
# ops/session_table.py; docs/sessions.md) ----------------------------------
declare("session.store.sessions", GAUGE,
        "live session slots registered in the store")
declare("session.store.inflight", GAUGE,
        "live inflight/awaiting-rel rows in the session table")
declare("session.store.tombstones", GAUGE,
        "acked (cleared) session rows awaiting compaction")
declare("session.ack.rides", COUNTER,
        "session write batches fused onto a serving launch "
        "(session_ack_step riding session_route_step: zero extra "
        "launches, zero extra readbacks)")
declare("session.ack.rows", COUNTER,
        "row writes (delivery inserts + PUBACK/PUBREC/PUBCOMP/PUBREL "
        "clears) applied via fused rides")
declare("session.ack.scatters", COUNTER,
        "session deltas applied via the segment scatter path instead "
        "(mesh engine, idle broker, or degraded device path)")
declare("session.sweep.device", COUNTER,
        "QoS retry/expiry sweeps that rode a serving launch")
declare("session.sweep.host", COUNTER,
        "host-array fallback sweeps (idle broker, non-fusing engine, "
        "or device path degraded)")
declare("session.sweep.due", COUNTER,
        "rows a sweep found due for retransmit (uncapped count)")
declare("session.redeliveries", COUNTER,
        "QoS1/2 retransmits sent from sweep hits (host re-verified)")
declare("session.expired.swept", COUNTER,
        "sessions the expiry sweep flagged past their deadline")
declare("session.resume.replayed", COUNTER,
        "sessions resumed via segment replay (store install: one full "
        "upload re-arms every inflight window)")

# retained-replay storm feed (broker/retained_feed.py)
declare("retained.storm.filters", COUNTER,
        "wildcard replay filters batched through the storm feed")
declare("retained.storm.fused", COUNTER,
        "storm jobs fused into a serving launch "
        "(fused_route_retained_step: zero extra launches)")
declare("retained.storm.flushed", COUNTER,
        "storm jobs answered by a standalone match_many flush (no "
        "publish launch arrived inside the window)")

declare("dispatch.fanout", HISTOGRAM,
        "deliveries per dispatched message", buckets=FANOUT_BUCKETS)
declare("dispatch.readback.bytes", HISTOGRAM,
        "device->host bytes read back per routed batch (compact slot "
        "lists + masked overflow rows, or full dense bitmaps)",
        buckets=READBACK_BUCKETS)
declare("dispatch.compact.rows", COUNTER,
        "batch rows dispatched from the compact slot list (no dense "
        "bitmap decode)")
declare("dispatch.compact.overflow.rows", COUNTER,
        "rows whose fan-out exceeded the Kslot cap (dense-row fallback "
        "via the masked second transfer)")

# -- device runtime telemetry (observe/device_watch.py) --------------------
declare("device.compile.count", COUNTER,
        "jit backend compiles observed (boot warmup + any retraces); "
        "nonzero growth in steady state is a retrace storm")
declare("device.compile.seconds", HISTOGRAM,
        "wall seconds per observed backend compile (window mean when "
        "only totals are available)",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("device.compile.cache_size", GAUGE,
        "summed jit-cache entries across @device_contract kernels and "
        "built mesh step programs (flat in steady state)")
declare("device.hbm.bytes", GAUGE,
        "live device memory: allocator bytes_in_use, or summed live "
        "array nbytes on backends without memory stats")
declare("device.transfer.bytes", COUNTER,
        "cumulative device->host readback bytes across all readback "
        "sites (rate = sustained link bandwidth consumed)")

# -- runtime race harness (observe/racetrack.py) ---------------------------
declare("racetrack.events", COUNTER,
        "accesses probed while the race harness is armed (the race test "
        "suite and chaos_soak; disarmed production cost is zero)")
declare("race.reports", COUNTER,
        "candidate data races reported by the armed lockset/HB detector "
        "(field + both stacks + locksets; zero unwaived is the gate)")

# -- shadow-replica replication audit (observe/replay_check.py) ------------
declare("replay.captures", COUNTER,
        "sync records captured by armed replay taps (full epoch uploads "
        "+ op-log delta suffixes; disarmed production cost is zero)")
declare("replay.syncs", COUNTER,
        "manager sync() calls observed while a replay tap is armed")
declare("replay.offers", COUNTER,
        "compaction offers observed while a replay tap is armed")
declare("replay.divergence", COUNTER,
        "owners whose shadow replica failed array-exact convergence "
        "(zero is the gate; any count means the op-log stream a standby "
        "would receive is incomplete)")
declare("analysis.replay.runs", COUNTER,
        "replication replay audits executed (ci_gate --replay and the "
        "chaos_soak probe)")
declare("analysis.replay.failures", COUNTER,
        "replay audits that diverged or missed the seeded "
        "incomplete-log negative control")
declare("analysis.wirecompat.runs", COUNTER,
        "wire-compatibility audits executed (ci_gate --audit replays "
        "the golden byte corpus through current decoders)")
declare("analysis.wirecompat.failures", COUNTER,
        "wirecompat audits that failed: corpus divergence, live-layout "
        "drift vs the format registry, an uncovered format, or a "
        "missed drift control")
declare("proto.registry.formats", GAUGE,
        "externalized wire/snapshot formats declared in "
        "emqx_tpu/proto/registry.py (each needs a version, a pinned "
        "digest, and golden-corpus coverage)")

# -- causal span tracing (observe/spans.py) --------------------------------
declare("trace.spans.sampled", COUNTER,
        "spans recorded into the ring (head-based sampling accepted)")
declare("trace.spans.dropped", COUNTER,
        "spans lost unfinished (open-registry overflow or a settle that "
        "found no open span)")

# -- semantic routing plane (docs/semantic_routing.md) ---------------------
declare("semantic.filters", GAUGE,
        "live embedding-filter subscriptions in the semantic table")
declare("semantic.hits", COUNTER,
        "qualifying semantic matches on the fused device path "
        "(pre-top-k; the uncapped sem_count sum per batch)")
declare("semantic.topk.truncated", COUNTER,
        "routed rows whose qualifying set exceeded topk (winners "
        "delivered, the tail dropped BY DESIGN)")
declare("semantic.host.batches", COUNTER,
        "batches/messages routed through the host twin (CPU fallback, "
        "per-message paths) instead of the fused kernel")
declare("semantic.host.matches", COUNTER,
        "semantic recipients resolved by the host twin")
declare("semantic.subscribe.rejected", COUNTER,
        "embedding filters ignored at subscribe (no semantic plane "
        "attached, or a $share filter)")
declare("semantic.embed.rejected", COUNTER,
        "per-message embeddings dropped as malformed (bad base64/JSON "
        "or a dimension mismatch)")

# -- rule engine (rules/engine.py; device predicates rules/compile.py) -----
declare("rules.matched", COUNTER,
        "rule evaluations whose FROM clause selected the event")
declare("rules.passed", COUNTER,
        "rule evaluations that passed WHERE and produced output rows")
declare("rules.failed", COUNTER,
        "rule evaluations that raised during SQL evaluation")
declare("rules.dropped", COUNTER,
        "rule evaluations dropped by WHERE (or an empty FOREACH) — on "
        "the device path these rows never built a host context")
declare("rules.device.batches", COUNTER,
        "settled batches whose compiled WHERE masks came from the "
        "serving launch (device rate)")
declare("rules.host.batches", COUNTER,
        "settled batches that fell back to the vectorized numpy WHERE "
        "evaluator (degraded/CPU batches, rule-set churn in flight)")

# -- profiling plane (observe/profiler.py; docs/observability.md
#    "Profiling & provenance") ---------------------------------------------
# the per-launch stage waterfall: prepare -> queue_wait -> launch ->
# device_execute -> readback -> host_dispatch. Observed per BATCH from
# the serving hot path (a handful of perf_counter reads), so the sum of
# stage means tracks the enqueue->settle latency the SLO controller
# steers on — the decomposition says WHERE a regression lives.
declare("profile.stage.prepare.seconds", HISTOGRAM,
        "waterfall: table snapshot + upload before the launch "
        "(Broker.adispatch_begin around dev.prepare)",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("profile.stage.queue_wait.seconds", HISTOGRAM,
        "waterfall: per-message enqueue -> batch-launch wait "
        "(window accumulation + lane queueing, BatchIngest)",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("profile.stage.launch.seconds", HISTOGRAM,
        "waterfall: host-side batch encode + kernel enqueue "
        "(DeviceRouter._route_prepared up to the readback boundary)",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("profile.stage.device_execute.seconds", HISTOGRAM,
        "waterfall: device program completion wait "
        "(block_until_ready at the readback boundary)",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("profile.stage.readback.seconds", HISTOGRAM,
        "waterfall: the coalesced device_get + host-side decode",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("profile.stage.host_dispatch.seconds", HISTOGRAM,
        "waterfall: settle-time host fan-out of one device batch "
        "(Broker._dispatch_device_results)",
        buckets=LATENCY_BUCKETS, unit="seconds")
# on-demand jax.profiler capture (REST-armed, bounded duration + file
# budget; disarmed cost is structurally zero — no hot-path hook exists)
declare("profile.captures", COUNTER,
        "completed jax.profiler trace captures (armed via "
        "POST /api/v5/profile)")
declare("profile.capture.seconds", HISTOGRAM,
        "armed duration of each completed capture",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("profile.capture.bytes", HISTOGRAM,
        "on-disk size of each completed capture (over-budget captures "
        "are deleted and record the size that tripped the bound)",
        buckets=READBACK_BUCKETS)
declare("profile.cost.kernels", GAUGE,
        "contract kernels covered by the last cost-analysis harvest "
        "(14 = the full registry)")

# -- hardware provenance (observe/provenance.py) ---------------------------
declare("provenance.proxy", GAUGE,
        "1 when the detected backend is NOT a TPU: every number this "
        "process emits is a CPU/GPU proxy, never a number of record")
declare("provenance.device.count", GAUGE,
        "devices visible to the backend this process measured on")

# -- per-kernel launch attribution (observe/profiler.py) -------------------
# one seconds+bytes pair per @device_contract registry name: each device
# launch observes its wall time + readback bytes into EVERY kernel that
# rode the program (fused launches list all of them), so "what does this
# kernel cost in production" is answerable per kernel without kernel-side
# instrumentation. Observation sites compose the names dynamically
# (f"device.kernel.{name}.seconds"); the declarations below are the
# MN-checked universe those names must land in.
declare("device.kernel.route_step.seconds", HISTOGRAM,
        "launch wall time for programs carrying route_step "
        "(match-only matcher path)",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("device.kernel.route_step.bytes", HISTOGRAM,
        "readback bytes attributed to route_step launches",
        buckets=READBACK_BUCKETS)
declare("device.kernel.shape_route_step.seconds", HISTOGRAM,
        "launch wall time for programs carrying shape_route_step "
        "(the serving-path flagship)",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("device.kernel.shape_route_step.bytes", HISTOGRAM,
        "readback bytes attributed to shape_route_step launches",
        buckets=READBACK_BUCKETS)
declare("device.kernel.sparse_shape_route_step.seconds", HISTOGRAM,
        "launch wall time for the serving program against a CSR "
        "subscriber table",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("device.kernel.sparse_shape_route_step.bytes", HISTOGRAM,
        "readback bytes attributed to sparse_shape_route_step launches",
        buckets=READBACK_BUCKETS)
declare("device.kernel.fused_route_retained_step.seconds", HISTOGRAM,
        "launch wall time for route launches fusing a retained-replay "
        "storm",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("device.kernel.fused_route_retained_step.bytes", HISTOGRAM,
        "readback bytes attributed to fused_route_retained_step "
        "launches",
        buckets=READBACK_BUCKETS)
declare("device.kernel.session_ack_step.seconds", HISTOGRAM,
        "launch wall time for route launches carrying the fused "
        "session-ack stage",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("device.kernel.session_ack_step.bytes", HISTOGRAM,
        "readback bytes attributed to session_ack_step launches",
        buckets=READBACK_BUCKETS)
declare("device.kernel.segment_scatter_insert.seconds", HISTOGRAM,
        "launch wall time of the fused segment delta-scatter "
        "(update path)",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("device.kernel.segment_scatter_insert.bytes", HISTOGRAM,
        "readback bytes attributed to segment_scatter_insert launches",
        buckets=READBACK_BUCKETS)
declare("device.kernel.compact_fanout_slots.seconds", HISTOGRAM,
        "launch wall time for programs carrying the dense fan-out "
        "compaction stage",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("device.kernel.compact_fanout_slots.bytes", HISTOGRAM,
        "readback bytes attributed to compact_fanout_slots launches",
        buckets=READBACK_BUCKETS)
declare("device.kernel.sparse_fanout_slots.seconds", HISTOGRAM,
        "launch wall time for programs carrying the CSR fan-out "
        "gather-union stage",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("device.kernel.sparse_fanout_slots.bytes", HISTOGRAM,
        "readback bytes attributed to sparse_fanout_slots launches",
        buckets=READBACK_BUCKETS)
declare("device.kernel.semantic_match_step.seconds", HISTOGRAM,
        "launch wall time for programs carrying the fused semantic "
        "similarity + top-k stage",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("device.kernel.semantic_match_step.bytes", HISTOGRAM,
        "readback bytes attributed to semantic_match_step launches",
        buckets=READBACK_BUCKETS)
declare("device.kernel.dist_step.seconds", HISTOGRAM,
        "launch wall time for the SPMD match-only mesh program",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("device.kernel.dist_step.bytes", HISTOGRAM,
        "readback bytes attributed to dist_step launches",
        buckets=READBACK_BUCKETS)
declare("device.kernel.dist_shape_step.seconds", HISTOGRAM,
        "launch wall time for the SPMD serving mesh program",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("device.kernel.dist_shape_step.bytes", HISTOGRAM,
        "readback bytes attributed to dist_shape_step launches",
        buckets=READBACK_BUCKETS)
declare("device.kernel.dist_fused_step.seconds", HISTOGRAM,
        "launch wall time for the SPMD serving program fusing a "
        "retained storm over the mesh",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("device.kernel.dist_fused_step.bytes", HISTOGRAM,
        "readback bytes attributed to dist_fused_step launches",
        buckets=READBACK_BUCKETS)
declare("device.kernel.sem_dist_shape_step.seconds", HISTOGRAM,
        "launch wall time for the SPMD serving program with the "
        "semantic stage",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("device.kernel.sem_dist_shape_step.bytes", HISTOGRAM,
        "readback bytes attributed to sem_dist_shape_step launches",
        buckets=READBACK_BUCKETS)
declare("device.kernel.sparse_dist_shape_step.seconds", HISTOGRAM,
        "launch wall time for the SPMD serving program against CSR "
        "shards",
        buckets=LATENCY_BUCKETS, unit="seconds")
declare("device.kernel.sparse_dist_shape_step.bytes", HISTOGRAM,
        "readback bytes attributed to sparse_dist_shape_step launches",
        buckets=READBACK_BUCKETS)
