"""Ban table + flapping detector.

Parity with the reference (apps/emqx/src/emqx_banned.erl: ban by
clientid/username/peerhost with until-timestamp, checked at connect;
emqx_flapping.erl: clients reconnecting more than N times inside a window
get auto-banned for ban_time).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.mqtt import packet as pkt


@dataclass
class BanEntry:
    kind: str  # 'clientid' | 'username' | 'peerhost'
    value: str
    by: str = "admin"
    reason: str = ""
    at: float = 0.0
    until: float = float("inf")


class Banned:
    def __init__(self) -> None:
        self._t: Dict[Tuple[str, str], BanEntry] = {}

    def add(self, entry: BanEntry) -> None:
        entry.at = entry.at or time.time()
        self._t[(entry.kind, entry.value)] = entry

    def delete(self, kind: str, value: str) -> bool:
        return self._t.pop((kind, value), None) is not None

    def entries(self) -> List[BanEntry]:
        return list(self._t.values())

    def is_banned(self, ci: Dict, now: Optional[float] = None) -> bool:
        now = now or time.time()
        for kind, key in (
            ("clientid", ci.get("client_id")),
            ("username", ci.get("username")),
            ("peerhost", str(ci.get("peerhost", ""))),
        ):
            if key is None:
                continue
            e = self._t.get((kind, key))
            if e is not None:
                if e.until <= now:
                    del self._t[(kind, key)]
                else:
                    return True
        return False

    def sweep(self, now: Optional[float] = None) -> int:
        now = now or time.time()
        gone = [k for k, e in self._t.items() if e.until <= now]
        for k in gone:
            del self._t[k]
        return len(gone)

    def check_connect(self, ci, p, acc=None):
        """'client.authenticate' high-priority gate."""
        if self.is_banned(ci):
            return (
                "stop",
                {"result": "deny", "reason_code": pkt.RC_BANNED},
            )
        return None

    def attach(self, hooks: Hooks) -> None:
        hooks.add("client.authenticate", self.check_connect, priority=1000)


class Flapping:
    """Auto-ban rapidly reconnecting clients (emqx_flapping.erl parity)."""

    def __init__(
        self,
        banned: Banned,
        max_count: int = 15,
        window: float = 60.0,
        ban_time: float = 300.0,
    ):
        self.banned = banned
        self.max_count = max_count
        self.window = window
        self.ban_time = ban_time
        self._hits: Dict[str, List[float]] = {}

    def on_disconnected(self, ci, reason=None) -> None:
        cid = ci.get("client_id")
        if not cid:
            return
        now = time.time()
        hits = [t for t in self._hits.get(cid, []) if now - t < self.window]
        hits.append(now)
        self._hits[cid] = hits
        if len(hits) >= self.max_count:
            self.banned.add(
                BanEntry(
                    kind="clientid",
                    value=cid,
                    by="flapping_detector",
                    reason=f"flapping: {len(hits)} disconnects in {self.window}s",
                    until=now + self.ban_time,
                )
            )
            del self._hits[cid]

    def sweep(self, now: Optional[float] = None) -> int:
        """Drop ids whose hit window has fully elapsed (memory bound)."""
        now = now or time.time()
        stale = [
            cid
            for cid, hits in self._hits.items()
            if not hits or now - hits[-1] >= self.window
        ]
        for cid in stale:
            del self._hits[cid]
        return len(stale)

    def attach(self, hooks: Hooks) -> None:
        hooks.add(
            "client.disconnected",
            lambda ci, reason: self.on_disconnected(ci, reason),
            priority=50,
        )
