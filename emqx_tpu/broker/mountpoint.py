"""Per-listener topic namespace prefixing.

Parity with the reference's emqx_mountpoint (apps/emqx/src/
emqx_mountpoint.erl): `mount` prefixes topics/filters on the way into the
broker, `unmount` strips the prefix on delivery, and `replvar` resolves
``${clientid}``/``${username}``/``${endpoint_name}`` placeholders once at
CONNECT (emqx_channel.erl:1369-1372 fix_mountpoint). Authorization checks
run on the client-visible (unmounted) topic, matching the reference's
pipeline ordering (authz before packet_to_message/do_subscribe mounting).

Shared-subscription filters mount the real topic inside the ``$share``
wrapper so group semantics survive the prefix.
"""

from __future__ import annotations

from typing import Optional

from emqx_tpu.ops import topics as T

_PLACEHOLDERS = ("clientid", "username", "endpoint_name")


def replvar(mountpoint: Optional[str], info: dict) -> Optional[str]:
    """Resolve ${var} placeholders against client info at CONNECT time.

    Unknown/absent vars leave the placeholder in place (reference
    feed_var/2 keeps the pattern when the value is undefined).
    """
    if not mountpoint:
        return mountpoint
    out = mountpoint
    for key in _PLACEHOLDERS:
        val = info.get(key)
        if key == "clientid" and val is None:
            val = info.get("client_id")
        if val is not None:
            out = out.replace("${" + key + "}", str(val))
    return out


def mount(mountpoint: Optional[str], topic: str) -> str:
    """Prefix a topic name or filter; $share filters mount the real part."""
    if not mountpoint:
        return topic
    group, real = T.parse_share(topic)
    if group is not None:
        return f"$share/{group}/{mountpoint}{real}"
    return mountpoint + topic


def unmount(mountpoint: Optional[str], topic: str) -> str:
    """Strip the prefix if present (no-op otherwise, like the reference)."""
    if not mountpoint:
        return topic
    if topic.startswith(mountpoint):
        return topic[len(mountpoint):]
    return topic
