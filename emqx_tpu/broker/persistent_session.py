"""Persistent sessions + session router.

Reference parity (SURVEY.md §2.1 emqx_persistent_session*/emqx_session_router,
§5.4(ii)):
- opt-in persistence for sessions with expiry_interval > 0: session
  metadata, subscriptions, and pending (undelivered) messages survive a
  broker restart (the reference persists messages at publish,
  emqx_broker.erl:213, against per-session undelivered/delivered/marker
  records; here the unit of durability is a session snapshot — pending
  queue + inflight — checkpointed on detach and on a flush interval)
- the **session router** is the separate route table the reference keeps
  for persistent sessions (emqx_session_router.erl): after a restart no
  channel exists, so restored sessions are re-attached to the broker with a
  detached deliverer that banks matched messages into the session mqueue
  until the client resumes (`resume_begin/resume_end` collapse to the
  in-process takeover handshake on a single node)
- durable broker state: retained messages, delayed messages, and the ban
  table snapshot/restore through the same FileKv (mnesia disc_copies
  analog, §5.4(iii)).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from emqx_tpu.broker.message import Message
from emqx_tpu.mqtt import packet as pkt
from emqx_tpu.storage.codec import (
    msg_from_json,
    msg_to_json,
    session_from_json,
    session_to_json,
)
from emqx_tpu.storage.kv import FileKv

NS_SESSIONS = "persistent_sessions"
NS_RETAINED = "retained"
NS_DELAYED = "delayed"
NS_BANNED = "banned"
NS_DEGRADE = "degrade"
NS_SEGMENTS = "segments"


def make_detached_deliverer(session, wal=None, client_id: str = ""):
    """Deliverer for a session with no live channel: bank QoS1/2 messages
    in the session queue for replay at resume (the reference's
    'undelivered' records). With a WAL attached, each banked message is
    also appended durably — the snapshot-to-snapshot crash window closes
    (emqx_broker.erl:213 persist-at-publish parity)."""

    def deliver(msg: Message, opts: pkt.SubOpts) -> None:
        qos = min(msg.qos, opts.qos)
        if qos == 0:
            return  # QoS0 to an offline session is dropped (spec behavior)
        import copy

        m = copy.copy(msg)
        m.qos = qos
        session.mqueue.in_(m)
        if wal is not None:
            wal.append(client_id, msg_to_json(m))

    return deliver


class SessionPersistence:
    """Checkpoints detached sessions; restores them (with routes) at boot.

    With a `MessageWal` attached, messages banked for detached sessions
    between checkpoints are appended durably and replayed over the
    snapshot at restore — closing the snapshot-to-snapshot crash window
    (the reference's persist-at-publish + undelivered records,
    emqx_persistent_session.erl:63-77)."""

    def __init__(self, broker, cm, kv: FileKv, session_config, wal=None):
        self.broker = broker
        self.cm = cm
        self.kv = kv
        self.session_config = session_config
        self.wal = wal
        self._dirty = False

    # -- hook + cm integration --------------------------------------------
    def attach(self, hooks) -> None:
        hooks.add(
            "client.disconnected", self._on_disconnected, tag="persistence"
        )
        hooks.add("session.detached", self._on_detached, tag="persistence")
        for hp in (
            "session.discarded",
            "session.terminated",
            "session.resumed",
            "session.takenover",
        ):
            hooks.add(hp, self._mark_dirty_any, tag="persistence")

    def _on_disconnected(self, ci, reason) -> None:
        self._dirty = True

    def _on_detached(self, cid: str) -> None:
        """The CM just parked this session: swap the (dead channel's)
        deliverers for the detached banker so every banked message hits
        the WAL from the moment of detach."""
        self._dirty = True
        ent = self.cm._detached.get(cid)
        if ent is None:
            return
        sess, _deadline = ent
        deliver = make_detached_deliverer(sess, self.wal, cid)
        for f, opts in sess.subscriptions.items():
            self.broker.subscribe(cid, cid, f, opts, deliver)

    def _mark_dirty_any(self, *args) -> None:
        self._dirty = True

    # -- checkpoint --------------------------------------------------------
    def flush(self, force: bool = False) -> bool:
        """Snapshot all detached sessions (called from housekeeping and at
        shutdown).

        Skips the write only when nothing could have changed: no lifecycle
        transition raised a hook (_dirty) AND there are no detached
        sessions whose queues mutate hook-free as offline messages bank."""
        if not (self._dirty or force or self.cm._detached):
            return False
        now = time.time()
        mono = time.monotonic()
        sessions = {}
        for cid, (sess, deadline) in self.cm._detached.items():
            snap = session_to_json(sess)
            # deadlines are monotonic (cm.py): persist the REMAINING
            # interval — a raw monotonic stamp means nothing after a
            # restart, and a wall deadline re-imports the clock-step
            # mass-expiry this snapshot format exists to avoid
            snap["expiry_remaining_s"] = max(0.0, deadline - mono)
            sessions[cid] = snap
        self.kv.write(NS_SESSIONS, {"at": now, "sessions": sessions})
        if self.wal is not None:
            # the snapshot now owns everything the WAL recorded
            self.wal.truncate()
        self._dirty = False
        return True

    # -- restore -----------------------------------------------------------
    def restore(self) -> int:
        """Rebuild detached sessions + their routes after a restart."""
        data = self.kv.read(NS_SESSIONS)
        if not data:
            return 0
        now = time.time()
        mono = time.monotonic()
        n = 0
        for cid, snap in data.get("sessions", {}).items():
            if "expiry_remaining_s" in snap:
                # downtime still counts against the interval: subtract
                # the wall time elapsed since the snapshot was cut
                remaining = float(snap["expiry_remaining_s"]) - max(
                    0.0, now - float(data.get("at", now))
                )
            else:
                # legacy snapshot: wall-clock deadline; rebase once
                remaining = snap.get("deadline", 0) - now
            if remaining <= 0:
                continue  # expired while the broker was down
            sess = session_from_json(snap, self.session_config)
            deliver = make_detached_deliverer(sess, self.wal, cid)
            for f, opts in sess.subscriptions.items():
                self.broker.subscribe(cid, cid, f, opts, deliver)
            self.cm._detached[cid] = (sess, mono + remaining)
            n += 1
        if self.wal is not None:
            # replay the post-snapshot suffix: messages banked after the
            # last checkpoint survive the crash (at-least-once)
            for cid, msg_json in self.wal.replay():
                ent = self.cm._detached.get(cid)
                if ent is not None:
                    ent[0].mqueue.in_(msg_from_json(msg_json))
        return n


class DurableState:
    """Retained / delayed / banned snapshot+restore (disc_copies analog)."""

    def __init__(self, kv: FileKv, retainer=None, delayed=None, banned=None,
                 degrade=None, segments=None):
        self.kv = kv
        self.retainer = retainer
        self.delayed = delayed
        self.banned = banned
        # DegradeController (broker/degrade.py): breaker states ride the
        # durable snapshot so a node restarting mid-degradation resumes
        # open/probing instead of hammering a still-broken fast path
        self.degrade = degrade
        # SegmentStateSnapshot (ops/segments.py): device-table host state
        # (route index, hot segments, subscriber bitmaps) checkpoints to
        # a sidecar file; the kv carries the pointer + generation so a
        # rolling upgrade restores tables instead of replaying subscribes
        self.segments = segments

    def flush(self) -> None:
        if self.degrade is not None:
            self.kv.write(NS_DEGRADE, {"paths": self.degrade.snapshot()})
        if self.segments is not None:
            self.kv.write(NS_SEGMENTS, self.segments.save())
        if self.retainer is not None:
            msgs = []
            for t in self.retainer.topics():
                m = self.retainer.get(t)
                if m is not None:
                    msgs.append(msg_to_json(m))
            self.kv.write(NS_RETAINED, {"messages": msgs})
        if self.delayed is not None:
            mono = time.monotonic()
            self.kv.write(
                NS_DELAYED,
                {
                    # remaining intervals, not deadlines: delayed dues
                    # are monotonic (broker/delayed.py) — `at` lets the
                    # restore charge the downtime against them
                    "at": time.time(),
                    "messages": [
                        {
                            "remaining_s": max(0.0, due - mono),
                            "msg": msg_to_json(m),
                        }
                        for due, m in self.delayed.pending()
                    ],
                },
            )
        if self.banned is not None:
            self.kv.write(
                NS_BANNED,
                {
                    "entries": [
                        {
                            "kind": e.kind,
                            "value": e.value,
                            "reason": e.reason,
                            "until": e.until,
                            "by": e.by,
                        }
                        for e in self.banned.entries()
                    ]
                },
            )

    def restore(self) -> Dict[str, int]:
        out = {"retained": 0, "delayed": 0, "banned": 0}
        if self.degrade is not None:
            data = self.kv.read(NS_DEGRADE)
            self.degrade.restore((data or {}).get("paths"))
        if self.segments is not None:
            # BEFORE session restore: re-subscribes then land as
            # refcount hits on the restored tables, not fresh builds
            restored = self.segments.load(self.kv.read(NS_SEGMENTS))
            out["segments"] = len(restored) if restored else 0
        if self.retainer is not None:
            data = self.kv.read(NS_RETAINED)
            for d in (data or {}).get("messages", []):
                m = msg_from_json(d)
                if not m.is_expired():
                    self.retainer.on_publish(m)
                    out["retained"] += 1
        if self.delayed is not None:
            data = self.kv.read(NS_DELAYED)
            now = time.time()
            mono = time.monotonic()
            downtime = max(0.0, now - float((data or {}).get("at", now)))
            for d in (data or {}).get("messages", []):
                m = msg_from_json(d["msg"])
                if m.is_expired():
                    continue
                if "remaining_s" in d:
                    due = mono + max(
                        0.0, float(d["remaining_s"]) - downtime
                    )
                else:  # legacy wall-deadline snapshot: rebase once
                    due = mono + max(0.0, float(d["due"]) - now)
                if self.delayed.load(due, m):
                    out["delayed"] += 1
        if self.banned is not None:
            from emqx_tpu.broker.banned import BanEntry

            data = self.kv.read(NS_BANNED)
            now = time.time()
            for d in (data or {}).get("entries", []):
                if d.get("until") and d["until"] <= now:
                    continue
                until = d.get("until")
                self.banned.add(
                    BanEntry(
                        kind=d["kind"],
                        value=d["value"],
                        reason=d.get("reason", ""),
                        until=until if until is not None else float("inf"),
                        by=d.get("by", "admin"),
                    )
                )
                out["banned"] += 1
        return out
