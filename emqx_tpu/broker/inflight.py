"""Inflight window for QoS1/2 deliveries (reference: emqx_inflight.erl).

Insertion-ordered dict keyed by packet id; entries carry the message, send
timestamp, and the QoS2 state ('publish' sent vs 'pubrel' phase).

Timestamps are `time.monotonic()`, NOT wall clock: retry/expiry decisions
are elapsed-time questions, and a wall-clock step (NTP correction, manual
set) would otherwise mass-expire every window at once — or freeze retries
entirely when the clock jumps backward. Serialization (storage/codec)
converts to/from ages, never raw stamps.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, Iterator, Optional, Tuple

from emqx_tpu.broker.message import Message


@dataclass
class InflightEntry:
    # In the QoS2 rel phase the payload is dropped but topic/qos/timestamp
    # metadata survive so completion hooks can report on the message
    msg: Optional[Message]
    phase: str  # 'publish' | 'pubrel'
    ts: float  # monotonic-clock stamp of the last (re)transmit


class Inflight:
    store_managed = False  # True on the session-store write-through view

    def __init__(self, max_size: int = 32):
        self.max_size = max_size
        self._d: Dict[int, InflightEntry] = {}

    def __len__(self) -> int:
        return len(self._d)

    def is_full(self) -> bool:
        return self.max_size > 0 and len(self._d) >= self.max_size

    def contains(self, packet_id: int) -> bool:
        return packet_id in self._d

    def get(self, packet_id: int) -> Optional[InflightEntry]:
        return self._d.get(packet_id)

    def insert(self, packet_id: int, msg: Message, phase: str = "publish"):
        if msg is not None:
            # slab-escape site: the window outlives the dispatch tick —
            # a SlabMessage must own its bytes, not pin the read buffer
            msg.own_buffers()
        self._d[packet_id] = InflightEntry(msg, phase, time.monotonic())

    def update(self, packet_id: int, phase: str) -> bool:
        e = self._d.get(packet_id)
        if e is None:
            return False
        e.phase = phase
        e.ts = time.monotonic()
        if phase == "pubrel" and e.msg is not None and e.msg.payload:
            # payload no longer needed after PUBREC; keep the metadata
            import copy

            m = copy.copy(e.msg)
            m.payload = b""
            e.msg = m
        return True

    def delete(self, packet_id: int) -> Optional[InflightEntry]:
        return self._d.pop(packet_id, None)

    def items(self) -> Iterator[Tuple[int, InflightEntry]]:
        return iter(list(self._d.items()))

    def retry_due(self, interval: float, now: Optional[float] = None):
        """Entries older than `interval` seconds, for retransmission.
        `now` must be a monotonic-clock reading when provided."""
        now = now or time.monotonic()
        return [
            (pid, e) for pid, e in self._d.items() if now - e.ts >= interval
        ]
