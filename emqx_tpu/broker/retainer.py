"""Retained messages (reference: apps/emqx_retainer, SURVEY.md §2.2).

Behavior parity with emqx_retainer_mnesia.erl: store on PUBLISH with
retain=1 (empty payload deletes, :28-65), deliver matching retained messages
on subscribe (wildcard `match_messages` scan :146-152), expiry sweep
(`clear_expired`), and a bounded message count.

Storage is a topic trie over the *retained topics* so a wildcard
subscription filter finds its matches by walking the trie with the filter
(the transpose of routing: filter-vs-stored-topics instead of
topic-vs-stored-filters). A TPU retained-replay kernel (BASELINE config #5:
5M retained, cold subscribe storm) slots in behind the same API later.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.broker.message import Message
from emqx_tpu.ops import topics as T


class _Node:
    __slots__ = ("children", "msg")

    def __init__(self):
        self.children: Dict[str, _Node] = {}
        self.msg: Optional[Message] = None


class Retainer:
    def __init__(
        self,
        max_retained: int = 1_000_000,
        max_payload: int = 1024 * 1024,
        device_threshold: int = 10_000,
        enable_device: bool = False,
    ):
        self._root = _Node()
        self._count = 0
        self.max_retained = max_retained
        self.max_payload = max_payload
        self.enabled = True
        # device replay index (models/retained_index.py): wildcard match
        # over big stores as batched kernel launches instead of a trie walk
        # per subscriber. Opt-in (the app enables it when router.enable_tpu
        # is on); used once the store crosses device_threshold, and only
        # while EVERY stored topic fits the device budget. NOTE: the first
        # wildcard match past the threshold pays the kernel compile on the
        # caller's thread — same pattern as the router's warmup.
        self.device_threshold = device_threshold
        self.enable_device = enable_device
        self._device = None
        self._device_unfit = 0
        # ('dp','tp') jax Mesh, set by the app BEFORE the first insert
        # when SPMD serving is on: the replay index then shards its
        # chunk mirrors over 'dp' (models/retained_index.py)
        self.mesh = None
        # RetainedStormFeed (broker/retained_feed.py), attached by the
        # app when the serving pipeline runs: wildcard-subscribe replays
        # batch into device storms that ride the publish pipeline's
        # fused launch instead of walking/launching per subscriber
        self.storm_feed = None

    def ensure_device(self) -> None:
        """Instantiate the device replay index eagerly (the app wires
        the storm feed against it before any retained insert)."""
        if self.enable_device and self._device is None:
            from emqx_tpu.models.retained_index import DeviceRetainedIndex

            self._device = DeviceRetainedIndex(mesh=self.mesh)

    def _dev_add(self, topic: str) -> None:
        if not self.enable_device:
            return
        self.ensure_device()
        if self._device is None:
            return
        if not self._device.add(topic):
            self._device_unfit += 1

    def _dev_remove(self, topic: str) -> None:
        if self._device is None:
            return
        if topic in self._device._rows:
            self._device.remove(topic)
        else:
            self._device_unfit = max(0, self._device_unfit - 1)

    def __len__(self) -> int:
        return self._count

    # -- store side -------------------------------------------------------
    def on_publish(self, msg: Message) -> None:
        """Called from the 'message.publish' pipeline for retain=1 messages."""
        if not self.enabled or not msg.retain or msg.topic.startswith("$SYS/"):
            return
        if msg.payload == b"":
            self.delete(msg.topic)
            return
        if len(msg.payload) > self.max_payload:
            return
        self._insert(msg)

    def _insert(self, msg: Message) -> None:
        # slab-escape site: the store holds messages indefinitely — a
        # retained SlabMessage must never pin its fabric read buffer
        msg.own_buffers()
        words = T.words(msg.topic)
        if self._count >= self.max_retained:
            # at capacity only an overwrite of an existing topic is allowed;
            # probe without allocating so rejected inserts leave no orphan
            # node chains behind
            node = self._root
            for w in words:
                node = node.children.get(w)
                if node is None:
                    return
            if node.msg is None:
                return
            node.msg = msg
            return
        node = self._root
        for w in words:
            node = node.children.setdefault(w, _Node())
        if node.msg is None:
            self._count += 1
            self._dev_add(msg.topic)
        node.msg = msg

    def delete(self, topic: str) -> bool:
        path: List[Tuple[_Node, str]] = []
        node = self._root
        for w in T.words(topic):
            child = node.children.get(w)
            if child is None:
                return False
            path.append((node, w))
            node = child
        if node.msg is None:
            return False
        node.msg = None
        self._count -= 1
        self._dev_remove(topic)
        for parent, w in reversed(path):
            child = parent.children[w]
            if child.msg is None and not child.children:
                del parent.children[w]
            else:
                break
        return True

    def get(self, topic: str) -> Optional[Message]:
        node = self._root
        for w in T.words(topic):
            node = node.children.get(w)
            if node is None:
                return None
        return node.msg

    # -- read side --------------------------------------------------------
    def match(self, filter_: str, now: Optional[float] = None) -> List[Message]:
        """All live retained messages whose topic matches `filter_`."""
        fw = T.words(filter_)
        out: List[Message] = []
        now = now or time.time()

        # device replay path for wildcard storms over big stores: batched
        # kernel launches instead of an O(store) trie walk per subscriber
        if (
            T.wildcard(filter_)
            and self._device is not None
            and self._device_unfit == 0
            and self._count >= self.device_threshold
        ):
            topics = self._device.match(filter_)
            if topics is not None:
                for t in topics:
                    m = self.get(t)
                    if m is not None and not m.is_expired(now):
                        out.append(m)
                return out

        def walk(node: _Node, i: int, root_level: bool) -> None:
            if i == len(fw):
                if node.msg is not None and not node.msg.is_expired(now):
                    out.append(node.msg)
                return
            w = fw[i]
            if w == "#":
                # matches parent and every descendant; skip $-roots at top
                def rec(n: _Node, skip_dollar: bool) -> None:
                    if n.msg is not None and not n.msg.is_expired(now):
                        out.append(n.msg)
                    for cw, c in n.children.items():
                        if skip_dollar and cw.startswith("$"):
                            continue
                        rec(c, False)

                if i == 0:
                    for cw, c in node.children.items():
                        if not cw.startswith("$"):
                            rec(c, False)
                else:
                    rec(node, False)
                return
            if w == "+":
                for cw, c in node.children.items():
                    if root_level and cw.startswith("$"):
                        continue
                    walk(c, i + 1, False)
                return
            c = node.children.get(w)
            if c is not None:
                walk(c, i + 1, False)

        walk(self._root, 0, True)
        return out

    def clear_expired(self, now: Optional[float] = None) -> int:
        now = now or time.time()
        removed: List[str] = []

        def sweep(node: _Node, prefix: List[str]) -> None:
            if node.msg is not None and node.msg.is_expired(now):
                removed.append("/".join(prefix))
            for w, c in list(node.children.items()):
                prefix.append(w)
                sweep(c, prefix)
                prefix.pop()

        sweep(self._root, [])
        for t in removed:
            self.delete(t)
        return len(removed)

    def all_messages(self, limit: Optional[int] = None) -> List[Message]:
        """Every stored message, INCLUDING '$'-rooted topics (a plain
        store walk, not wildcard matching — cluster bootstrap needs the
        full set, which `match('#')` would under-report per MQTT rules)."""
        out: List[Message] = []

        def walk(node: _Node) -> None:
            if limit is not None and len(out) >= limit:
                return
            if node.msg is not None:
                out.append(node.msg)
            for c in node.children.values():
                walk(c)

        walk(self._root)
        return out

    def messages_page(
        self, after: Optional[str], limit: int
    ) -> Tuple[List[Message], Optional[str]]:
        """Ordered page of stored messages strictly AFTER topic `after`
        (None = from the start): the cursor walk behind cluster
        bootstrap and REST pagination (paged-read parity with
        emqx_retainer_mnesia.erl:146-152). Ordering is word-tuple
        lexicographic (parent before children, children sorted), and the
        resume descent prunes subtrees before the cursor — a page costs
        O(limit * depth + cursor depth), never a full store walk.
        Returns (msgs, next_cursor); next_cursor None = no more pages."""
        out: List[Message] = []

        def walk(node: _Node, bound) -> None:
            # bound: remaining cursor words under this subtree;
            # None = subtree is entirely after the cursor,
            # []   = cursor topic ends exactly at this node
            if len(out) >= limit:
                return
            if node.msg is not None and bound is None:
                out.append(node.msg)
            if bound:
                w0 = bound[0]
                for w in sorted(node.children):
                    if len(out) >= limit:
                        return
                    if w < w0:
                        continue
                    walk(
                        node.children[w],
                        bound[1:] if w == w0 else None,
                    )
            else:
                for w in sorted(node.children):
                    if len(out) >= limit:
                        return
                    walk(node.children[w], None)

        walk(self._root, after.split("/") if after else None)
        nxt = out[-1].topic if len(out) >= limit else None
        return out, nxt

    def topics(self) -> List[str]:
        out: List[str] = []

        def walk(node: _Node, prefix: List[str]) -> None:
            if node.msg is not None:
                out.append("/".join(prefix))
            for w, c in node.children.items():
                prefix.append(w)
                walk(c, prefix)
                prefix.pop()

        walk(self._root, [])
        return out

    # -- wiring -----------------------------------------------------------
    def attach(self, hooks: Hooks) -> None:
        """Install on the reference's hookpoints
        ('message.publish' + 'session.subscribed', emqx_retainer.erl)."""

        def on_pub(msg):
            if msg is not None:
                self.on_publish(msg)
            return None

        def on_sub(client_info, filter_, opts, channel=None):
            # delivery handled by the channel integration (channel passes
            # itself; standalone tests may not)
            if channel is None:
                return
            group, real = T.parse_share(filter_)
            if group is not None:
                return  # no retained delivery for shared subs (spec)
            if opts.retain_handling == 2:
                return
            if opts.retain_handling == 1 and getattr(opts, "_existing", False):
                return
            if self._storm_eligible(real):
                # device-scale wildcard replay: batch it through the
                # storm feed (rides the serving pipeline's fused launch)
                # instead of blocking the SUBSCRIBE hook on an O(store)
                # device pass per subscriber. Replay lands asynchronously
                # — the spec allows retained delivery any time after the
                # subscription is established.
                import asyncio

                asyncio.ensure_future(
                    self._replay_batched(real, opts, channel)
                )
                return
            self._deliver_retained(self.match(real), opts, channel)

        hooks.add("message.publish", lambda msg: on_pub(msg), priority=100)
        hooks.add("session.subscribed", on_sub)

    def _storm_eligible(self, real: str) -> bool:
        """Wildcard filter that the device replay path would serve AND a
        storm feed is attached (serving pipeline running)."""
        return (
            self.storm_feed is not None
            and T.wildcard(real)
            and self._device is not None
            and self._device_unfit == 0
            and self._count >= self.device_threshold
            and len(T.words(real)) <= self._device.max_levels
        )

    def _deliver_retained(self, msgs, opts, channel) -> None:
        import copy

        for m in msgs:
            mm = copy.copy(m)
            mm.headers = dict(m.headers, retained=True)
            channel.handle_deliver(mm, opts)

    async def _replay_batched(self, real: str, opts, channel) -> None:
        """One batched replay: await the storm feed's answer (a fused
        serving launch or the standalone flush), fall back to the
        authoritative CPU walk when the device pass could not serve it.
        Topics re-fetch from the live store, so a concurrent delete
        costs a lookup, never a stale replay."""
        try:
            topics = await self.storm_feed.submit(real)
        except Exception:  # noqa: BLE001 — replay must not kill the task
            topics = None
        now = time.time()
        if topics is None:
            msgs = self.match(real, now)
        else:
            msgs = []
            for t in topics:
                m = self.get(t)
                if m is not None and not m.is_expired(now):
                    msgs.append(m)
        try:
            self._deliver_retained(msgs, opts, channel)
        except Exception:  # noqa: BLE001 — detached task: a subscriber
            # gone mid-replay must not surface as an unretrieved error
            import logging

            logging.getLogger("emqx_tpu.retainer").debug(
                "retained replay delivery failed (subscriber gone?)",
                exc_info=True,
            )
