"""Device-resident session store: inflight windows & QoS state on segments.

`ops/session_table.py` is the table; this module is the broker-side owner
that puts it on the serving path (ROADMAP item 2, docs/sessions.md):

- **write-through**: live `Session` objects keep their exact dict-era
  semantics (the degrade-ladder fallback — `session.device_store` off
  changes nothing), but every inflight mutation ALSO lands in the
  host-authoritative `SessionTable`, op-logged for the device mirror.
- **fused ack clears**: the op-log suffix does not pay its own scatter
  launch. `take_rider()` packages it as a `SessionRider`;
  `Broker.adispatch_begin` hands the rider to the device engine, which
  fuses `session_ack_step` into the SAME program as routing
  (`session_route_step`) — PUBACK/PUBREC/PUBCOMP/PUBREL batches become
  scatter clears riding the launch the batch was paying anyway, and the
  sweep outputs ride the same coalesced readback (no extra launch, no
  extra transfer: asserted the way PR 6 asserts one-transfer-per-batch).
- **device sweeps**: QoS1/2 retransmit scans and session-expiry checks
  are a whole-table device sweep (`sweep_k` compacted row ids), not a
  per-client dict walk; every device-reported row is RE-VERIFIED against
  the authoritative host arrays before anything is sent (the staleness
  net the dispatch path already uses for subscriber slots).
- **mass resume = segment replay**: `capture()`/`install()` checkpoint
  the host arrays + message slab through `SegmentStateSnapshot`; a
  restored store re-arms millions of inflight windows with ONE full
  upload on the next sync — no per-session Python object is rebuilt
  until (unless) that client actually reconnects.

Threading: every mutator runs on the event loop (single-writer: loop).
`route_prepared` on the `tpu-dispatch` executor only reads the rider's
immutable arrays; commit/abort happen back on the loop in the broker's
`_complete`, so at most ONE rider is ever outstanding.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, NamedTuple, Optional

import numpy as np

from emqx_tpu.broker.inflight import Inflight
from emqx_tpu.ops.nfa import _next_pow2
from emqx_tpu.ops.segments import DeviceSegmentManager
from emqx_tpu.ops.session_table import (
    ST_AWAIT_REL,
    ST_PUBLISH,
    ST_PUBREL,
    SessionSegmentOwner,
    SessionTable,
)

# incoming (client -> broker) QoS2 packet ids live at pid + PID_SPACE so
# they can never collide with the outgoing window's ids in the one table
PID_SPACE = 1 << 16


class SessionRider(NamedTuple):
    """One op-log suffix packaged to ride a serving launch."""

    arrays: Dict  # current device mirror (immutable snapshot)
    idxs: Dict  # lane -> int32 write indices (pow2-padded)
    vals: Dict  # lane -> int32 write values
    clock: np.ndarray  # int32 [2]: (now_ds, retry_ds)
    pos: int  # op-log position the produced arrays represent
    epoch: int  # source epoch the rider was taken at
    sweep_k: int  # 0 = no sweep stage this launch
    rows: int  # distinct row writes riding (telemetry)


class SessionStepOut(NamedTuple):
    """Device outputs of one fused session stage (RouteResult.session)."""

    arrays: Dict  # updated device mirror (stays on device)
    due: Optional[np.ndarray]  # [sweep_k] row ids, -1 pad (None: no sweep)
    due_count: int  # uncapped due total (overflow => sweep again)
    expired: Optional[np.ndarray]  # [sweep_k] session slots, -1 pad
    expired_count: int


class StoreInflight(Inflight):
    """`Inflight` with write-through to the session table. The dict view
    stays authoritative for the live channel (identical semantics to the
    host-only path — the equivalence property the tests pin); the table
    write-through is what makes the aggregate state device-resident."""

    store_managed = True

    def __init__(self, store: "SessionStore", slot: int, max_size: int = 32):
        super().__init__(max_size)
        self.store = store
        self.slot = slot

    def insert(self, packet_id: int, msg, phase: str = "publish"):
        super().insert(packet_id, msg, phase)
        self.store.inflight_insert(self.slot, packet_id, msg, phase)

    def update(self, packet_id: int, phase: str) -> bool:
        ok = super().update(packet_id, phase)
        if ok:
            self.store.inflight_phase(self.slot, packet_id, phase)
        return ok

    def delete(self, packet_id: int):
        e = super().delete(packet_id)
        if e is not None:
            self.store.inflight_delete(self.slot, packet_id)
        return e


class SessionStore:
    """Owner of one `SessionTable` + its device mirror + message slab."""

    def __init__(
        self,
        capacity: int = 4096,
        sweep_slots: int = 1024,
        retry_interval: float = 30.0,
        metrics=None,
        mesh=None,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.table = SessionTable(capacity=capacity)
        placement = None
        if mesh is not None:
            # session rows shard over 'dp' like retained chunks — the
            # placement hook is the one place the layout is declared
            # (PR 10 discipline; parallel/mesh.session_placement)
            from emqx_tpu.parallel.mesh import session_placement

            placement = session_placement(mesh)
        self.manager = DeviceSegmentManager(
            placement=placement, free_retired=True, metrics=metrics, name="sessions"
        )
        self.metrics = metrics
        self.sweep_slots = max(16, _next_pow2(sweep_slots))
        self.retry_ds = max(1, int(retry_interval * 10))
        self._clock = clock or time.monotonic
        self._t0 = self._clock()
        # message slab: mid -> Message (payloads stay host-side; the
        # table's sess_mid lane indexes here). A free-listed LIST, not a
        # dict — no per-entry hashing at million-entry scale.
        self._slab: List = []
        self._free_mids: List[int] = []
        # client registry: cid -> slot + the dense reverse map
        self._slots: Dict[str, int] = {}
        self._slot_cid: List[Optional[str]] = []
        self._free_slots: List[int] = []
        # slot -> resend(pid, state, msg) for LIVE channels only
        self._bind: Dict[int, Callable] = {}
        # offline-queue length lane bookkeeping rides the table via
        # note_queue_len (slot_qlen is host gauge state, not a lane —
        # the device lanes carry the delivery-guarantee state)
        self._rider_out = False  # single-writer: loop
        self._want_sweep = False  # single-writer: loop
        self._last_ride = 0.0  # single-writer: loop
        self.on_expired: Optional[Callable] = None  # cids past expiry
        self.restored = 0

    # -- clock -------------------------------------------------------------
    def now_ds(self) -> int:
        return int((self._clock() - self._t0) * 10)

    # -- session registry --------------------------------------------------
    def attach(self, client_id: str) -> int:
        slot = self._slots.get(client_id)
        if slot is not None:
            return slot
        if self._free_slots:
            slot = self._free_slots.pop()
            self._slot_cid[slot] = client_id
        else:
            slot = len(self._slot_cid)
            self._slot_cid.append(client_id)
        self._slots[client_id] = slot
        if self.metrics is not None:
            self.metrics.gauge_set(
                "session.store.sessions", len(self._slots)
            )
        return slot

    def slot_of(self, client_id: str) -> Optional[int]:
        return self._slots.get(client_id)

    def bulk_attach(self, client_ids) -> np.ndarray:
        """Vectorized slot registration for mass loads (bench/restore
        tooling): appends fresh slots in one pass (free list untouched)."""
        base = len(self._slot_cid)
        new = [c for c in client_ids if c not in self._slots]
        self._slots.update({c: base + i for i, c in enumerate(new)})
        self._slot_cid.extend(new)
        if self.metrics is not None:
            self.metrics.gauge_set(
                "session.store.sessions", len(self._slots)
            )
        return np.asarray(
            [self._slots[c] for c in client_ids], np.int64
        )

    def bulk_load(self, client_ids, msgs, pids=None) -> np.ndarray:
        """Mass inflight load (the session_storm bench's build phase):
        one QoS1 publish-phase row per client, placed vectorized with
        ONE epoch bump. Returns the placed row ids."""
        slots = self.bulk_attach(client_ids)
        mids = np.asarray([self._put_msg(m) for m in msgs], np.int64)
        n = len(slots)
        pids = (
            np.asarray(pids, np.int64)
            if pids is not None
            else np.ones(n, np.int64)
        )
        now = self.now_ds()
        rows = self.table.bulk_insert(
            slots, pids, np.full(n, ST_PUBLISH, np.int64),
            np.full(n, now, np.int64), mids,
        )
        self._gauges()
        return rows

    def make_inflight(self, slot: int, max_size: int) -> StoreInflight:
        return StoreInflight(self, slot, max_size)

    def bind(self, slot: int, resend: Callable) -> None:
        """Register a live channel's resend(pid, state, msg) callback —
        sweep hits on unbound (offline) slots are skipped, exactly like
        the dict path never retries a detached session."""
        self._bind[slot] = resend

    def unbind(self, slot: int) -> None:
        self._bind.pop(slot, None)

    def set_expiry(self, client_id: str, deadline_s: float) -> None:
        """Arm the session-expiry lane (deadline on the store clock;
        0/negative disarms — e.g. at resume)."""
        slot = self._slots.get(client_id)
        if slot is None:
            return
        ds = 0
        if deadline_s > 0:
            ds = max(1, self.now_ds() + int(deadline_s * 10))
        self.table.set_expiry(slot, ds)

    def drop_session(self, client_id: str) -> None:
        """Terminal cleanup: clear every row the slot owns, free its
        slab messages, recycle the slot."""
        slot = self._slots.pop(client_id, None)
        if slot is None:
            return
        for row in self.table.rows_of_slot(slot):
            mid = self.table.clear(int(row))
            self._drop_mid(mid)
        self.table.set_expiry(slot, 0)
        self._slot_cid[slot] = None
        self._bind.pop(slot, None)
        self._free_slots.append(slot)
        if self.metrics is not None:
            self.metrics.gauge_set(
                "session.store.sessions", len(self._slots)
            )

    # -- message slab ------------------------------------------------------
    def _put_msg(self, msg) -> int:
        if msg is None:
            return -1
        # slab-escape site: the message slab holds entries until ack —
        # a SlabMessage must own its bytes before landing here
        msg.own_buffers()
        if self._free_mids:
            mid = self._free_mids.pop()
            self._slab[mid] = msg
        else:
            mid = len(self._slab)
            self._slab.append(msg)
        return mid

    def _drop_mid(self, mid: int) -> None:
        if 0 <= mid < len(self._slab) and self._slab[mid] is not None:
            self._slab[mid] = None
            self._free_mids.append(mid)

    def _get_msg(self, mid: int):
        if 0 <= mid < len(self._slab):
            return self._slab[mid]
        return None

    # -- inflight write-through (loop thread) ------------------------------
    def inflight_insert(self, slot: int, pid: int, msg, phase: str) -> None:
        state = ST_PUBREL if phase == "pubrel" else ST_PUBLISH
        self.table.insert(
            slot, pid, state, self.now_ds(), self._put_msg(msg)
        )
        self._gauges()

    def inflight_phase(self, slot: int, pid: int, phase: str) -> None:
        row = self.table._find(slot, pid)
        if row < 0:
            return
        if phase == "pubrel":
            # rel phase: the payload is done (PUBREC confirmed receipt);
            # only the PUBREL handshake retries from here
            self._drop_mid(int(self.table.sess_mid[row]))
            self.table.set_state(row, ST_PUBREL, self.now_ds(), mid=-1)
        else:
            self.table.set_state(row, ST_PUBLISH, self.now_ds())

    def touch_inflight(self, slot: int, pid: int) -> None:
        """Refresh the table's retransmit stamp after a host-side resend."""
        row = self.table._find(slot, pid)
        if row >= 0:
            self.table.touch(row, self.now_ds())

    def inflight_delete(self, slot: int, pid: int) -> None:
        row = self.table._find(slot, pid)
        if row < 0:
            return
        self._drop_mid(self.table.clear(row))
        self._gauges()

    # incoming QoS2 (client -> broker): awaiting-rel rows ride the same
    # table at pid + PID_SPACE, so PUBREL releases are fused clears too
    def await_rel(self, slot: int, pid: int) -> None:
        self.table.insert(
            slot, pid + PID_SPACE, ST_AWAIT_REL, self.now_ds(), -1
        )

    def release_rel(self, slot: int, pid: int) -> None:
        row = self.table._find(slot, pid + PID_SPACE)
        if row >= 0:
            self.table.clear(row)

    def _gauges(self) -> None:
        if self.metrics is not None:
            self.metrics.gauge_set("session.store.inflight", self.table.live)
            self.metrics.gauge_set(
                "session.store.tombstones", self.table.tombstones
            )

    # -- the fused-launch rider (loop thread) ------------------------------
    def take_rider(self) -> Optional[SessionRider]:
        """Package the op-log suffix (+ a pending sweep request) for the
        next serving launch; None when there is nothing to ride or a
        rider is already in flight. A structural event (growth, first
        upload) full-syncs HERE, on the loop, off the launch path."""
        if self._rider_out:
            return None
        want_sweep = self._want_sweep
        peek = self.manager.peek_delta(self.table)
        if peek is None:
            if not (self.table.oplog or want_sweep or
                    not self.manager.has_mirror()):
                return None
            self.manager.sync(self.table)  # full resync (rare)
            peek = self.manager.peek_delta(self.table)
            if peek is None:
                return None
        arrays, per, pos, epoch = peek
        sweep_k = self.sweep_slots if want_sweep else 0
        if not per and not sweep_k:
            return None
        idxs: Dict[str, np.ndarray] = {}
        vals: Dict[str, np.ndarray] = {}
        rows = 0
        for name, writes in per.items():
            n = len(writes)
            rows += n
            npad = max(16, _next_pow2(n))
            ix = np.empty(npad, np.int32)
            vv = np.empty(npad, np.int32)
            ix[:n] = np.fromiter(writes.keys(), np.int64, n)
            vv[:n] = np.fromiter(writes.values(), np.int64, n)
            # pad repeats the last write — idempotent, keeps programs
            # keyed on pow2 delta buckets (the segment-scatter rule);
            # per-lane entries always carry >= 1 write
            ix[n:] = ix[n - 1]
            vv[n:] = vv[n - 1]
            idxs[name] = ix
            vals[name] = vv
        clock = np.asarray([self.now_ds(), self.retry_ds], np.int32)
        self._rider_out = True
        self._want_sweep = False
        return SessionRider(
            arrays, idxs, vals, clock, pos, epoch, sweep_k, rows
        )

    def commit(self, rider: SessionRider, out: SessionStepOut) -> None:
        """Back on the loop after a successful launch: adopt the updated
        device mirror and act on the sweep outputs (every hit host-
        re-verified before anything is transmitted)."""
        self._rider_out = False
        self._last_ride = self._clock()
        self.manager.adopt(out.arrays, rider.pos, rider.epoch)
        if self.metrics is not None:
            self.metrics.inc("session.ack.rides")
            if rider.rows:
                self.metrics.inc("session.ack.rows", rider.rows)
        if rider.sweep_k and out.due is not None:
            if self.metrics is not None:
                self.metrics.inc("session.sweep.device")
                self.metrics.inc(
                    "session.sweep.due", int(out.due_count)
                )
            self._redeliver(out.due[out.due >= 0])
            self._expire(out.expired[out.expired >= 0])
            if (
                out.due_count > rider.sweep_k
                or out.expired_count > rider.sweep_k
            ):
                # flood overflowed the compact width: sweep again on
                # the next launch (counts are uncapped by contract)
                self._want_sweep = True

    def abort(self, rider: SessionRider) -> None:
        """Launch failed/degraded: the mirror never advanced, so the
        suffix simply rides the next rider (or the manager's scatter) —
        host arrays are authoritative, nothing is lost."""
        self._rider_out = False

    # -- sweeps ------------------------------------------------------------
    def request_sweep(self) -> None:
        self._want_sweep = True

    def tick(self, fused_path: bool = True) -> None:
        """Housekeeping: arm a device sweep to ride the next launch; on
        engines without session fusion (mesh) — or when no launch has
        ridden for a while (idle broker) — fall back to the host scan
        and the manager's own scatter path so nothing waits on traffic."""
        self._gauges()
        if fused_path:
            self._want_sweep = True
            if self._clock() - self._last_ride < 2.0:
                return
        # idle / non-fusing: authoritative host sweep + mirror catch-up
        if not self._rider_out and (
            self.table.oplog or not self.manager.has_mirror()
        ):
            self.manager.sync(self.table)
            if self.metrics is not None:
                self.metrics.inc("session.ack.scatters")
        self.host_sweep()

    def host_sweep(self) -> int:
        """The authoritative (and fallback) retransmit scan: one
        vectorized pass over the host arrays — never a dict walk."""
        now = self.now_ds()
        due = self.table.due_rows(now, self.retry_ds)
        if self.metrics is not None:
            self.metrics.inc("session.sweep.host")
            if len(due):
                self.metrics.inc("session.sweep.due", int(len(due)))
        n = self._redeliver(due)
        self._expire(self.table.expired_slots(now))
        return n

    def _redeliver(self, rows) -> int:
        """Retransmit due rows through the bound channels.

        The re-verify against the authoritative host table (rows can
        clear while a sweep is in flight — same staleness net as
        subscriber slots) is ONE vectorized mask over the row arrays,
        not a per-row field walk. Surviving rows then group per bound
        channel: a channel exposing `_store_resend_batch` (the real
        Channel; docs/protocol_plane.md) gets ALL its due rows in one
        call — one slab-serializer pass, one writelines — and stamps
        refresh via `touch_many`. Plain per-row callbacks keep the
        legacy contract (the degrade/compat path)."""
        t = self.table
        rows = np.asarray(rows, np.int64)
        if not rows.size:
            return 0
        now = self.now_ds()
        slot_a = t.sess_slot[rows]
        state_a = t.sess_state[rows]
        ok = (
            (slot_a >= 0)
            & ((state_a == ST_PUBLISH) | (state_a == ST_PUBREL))
            & ((now - t.sess_ts[rows]) >= self.retry_ds)
            & (t.sess_pid[rows] < PID_SPACE)  # incoming QoS2 never
        )
        if not ok.any():
            return 0
        rows = rows[ok]
        slots_l = slot_a[ok].tolist()
        states_l = state_a[ok].tolist()
        pids_l = t.sess_pid[rows].tolist()
        mids_l = t.sess_mid[rows].tolist()
        rows_l = rows.tolist()
        bind = self._bind
        slab = self._slab
        n_slab = len(slab)
        n = 0
        touched: List[int] = []
        # per-channel batches: OWNER id -> [batch_fn, items, row ids]
        # (grouped by the owning channel — bound methods are distinct
        # objects per bind, so keying on the callback would shatter one
        # sink's rows into single-item batches). cb_ent memoizes the
        # owner/batch resolution per callback object: the flood loop
        # then pays one dict get per row, not two getattrs.
        batches: Dict[int, list] = {}
        cb_ent: Dict[int, object] = {}
        for i, slot in enumerate(slots_l):
            cb = bind.get(slot)
            if cb is None:
                continue  # offline session: nothing to transmit to
            ent = cb_ent.get(id(cb))
            if ent is None:
                owner = getattr(cb, "__self__", cb)
                batch_fn = getattr(owner, "_store_resend_batch", None)
                if batch_fn is None:
                    ent = cb_ent[id(cb)] = 0  # legacy per-row marker
                else:
                    ent = batches.get(id(owner))
                    if ent is None:
                        ent = batches[id(owner)] = [batch_fn, [], []]
                    cb_ent[id(cb)] = ent
            mid = mids_l[i]
            msg = slab[mid] if 0 <= mid < n_slab else None
            if ent != 0:
                ent[1].append((pids_l[i], states_l[i], msg))
                ent[2].append(rows_l[i])
                continue
            try:
                if not cb(pids_l[i], states_l[i], msg):
                    continue
            except Exception:  # noqa: BLE001 — one dead sink, not the sweep
                continue
            t.touch(rows_l[i], now)
            n += 1
        for batch_fn, items, rws in batches.values():
            try:
                sent = batch_fn(items)
            except Exception:  # noqa: BLE001 — one dead sink, not the sweep
                continue
            touched.extend(r for r, s in zip(rws, sent) if s)
            n += sum(map(bool, sent))
        if touched:
            t.touch_many(touched, now)
        if n and self.metrics is not None:
            self.metrics.inc("session.redeliveries", n)
        return n

    def _expire(self, slots) -> None:
        if not len(slots):
            return
        cids = []
        for slot in np.asarray(slots).tolist():
            slot = int(slot)
            if slot < len(self._slot_cid) and self._slot_cid[slot]:
                cids.append(self._slot_cid[slot])
        if self.metrics is not None and cids:
            self.metrics.inc("session.expired.swept", len(cids))
        if self.on_expired is not None and cids:
            self.on_expired(cids)

    # -- compaction + durability -------------------------------------------
    def compaction_owner(self, tombstone_frac: float = 0.25):
        return SessionSegmentOwner(
            self.table,
            self.manager,
            placement=self.manager._placement,
            tombstone_frac=tombstone_frac,
        )

    def capture(self) -> Dict:
        """Loop-thread checkpoint for `SegmentStateSnapshot` — the whole
        store as plain numpy + lists (mnesia disc_copies analog)."""
        return {
            "table": self.table,
            "slab": self._slab,
            "free_mids": self._free_mids,
            "slots": self._slots,
            "slot_cid": self._slot_cid,
            "free_slots": self._free_slots,
            "t0_age_ds": self.now_ds(),
        }

    def install(self, state: Dict) -> int:
        """Mass session resume as a segment replay: swap the restored
        host state in; the next sync is ONE full upload and every
        inflight window in the table is live again — zero per-session
        Python objects rebuilt."""
        self.table = state["table"]
        self._slab = state["slab"]
        self._free_mids = state["free_mids"]
        self._slots = state["slots"]
        self._slot_cid = state["slot_cid"]
        self._free_slots = state["free_slots"]
        # rebase the store clock so restored deciseconds stay comparable
        self._t0 = self._clock() - state.get("t0_age_ds", 0) / 10.0
        self.table._bump()  # force the next sync to be a full re-upload
        self._rider_out = False
        self.restored = len(self._slots)
        if self.metrics is not None:
            self.metrics.inc("session.resume.replayed", self.restored)
            self.metrics.gauge_set(
                "session.store.sessions", len(self._slots)
            )
        self._gauges()
        return self.restored

    def status(self) -> Dict:
        """Feeds the hotpath REST `session` block + housekeeping gauges."""
        return {
            "sessions": len(self._slots),
            "inflight": self.table.live,
            "tombstones": self.table.tombstones,
            "capacity": self.table._cap,
            "slab": len(self._slab) - len(self._free_mids),
            "full_resyncs": self.manager.full_resyncs,
            "delta_launches": self.manager.delta_launches,
        }
