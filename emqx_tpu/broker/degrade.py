"""Graceful-degradation controller: per-path circuit breakers.

The serving pipeline's fast paths (device route launch, delta-sync,
cluster forward) each get a breaker walking the ladder

    closed ──(retries exhausted x failure_threshold)──▶ open/degraded
      ▲                                                    │
      │  probe_successes consecutive                       │ open_secs
      └──────── successful probes ◀── half-open ◀──────────┘

driving REAL fallbacks rather than error pages: an open device breaker
serves whole batches from the authoritative CPU trie
(`Broker.adispatch_begin` / `dispatch_batch_folded`); an open cluster
breaker fails sends fast instead of paying the full deadline per
message (`cluster/tcp_transport.py`); the ingest window sheds enqueues
while the device breaker is open or `Olp.is_overloaded()` holds
(backpressure instead of unbounded queue growth). Half-open probes send
ONE real batch down the fast path — re-warming the jit — and recovery
closes the breaker.

Every transition sets the declared `degrade.state.*` gauge (0 closed,
1 half-open, 2 open), counts `degrade.trips.*` / `degrade.probe.ok` /
`degrade.probe.fail`, and emits a `degrade.transition` span event so
the causal traces from PR 5 show *why* a message took the slow path.

Reference analog: the reference degrades via overload hibernation and
`emqx_olp`; a batched TPU pipeline needs the batch-granular ladder
because one wedged launch stalls thousands of publishes at once.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Callable, Dict, Iterator, Optional

log = logging.getLogger("emqx_tpu.degrade")

CLOSED = "closed"
HALF_OPEN = "half_open"
OPEN = "open"


class IngestShed(RuntimeError):
    """The ingest gate refused an enqueue (overload / open breaker past
    the queue bound). Backpressure, not loss: the publisher's PUBACK
    fails and a QoS>=1 client retries — the queue never grows unbounded
    behind a broken device path."""

# gauge encoding for degrade.state.* (alert on > 0)
STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class Breaker:
    """One path's breaker. Thread-safe: the device path records results
    from executor threads, the cluster path from bus/forward threads.

    `allow()` is the gate callers consult before taking the fast path;
    it returns True in closed state, admits exactly one probe at a time
    in half-open, and flips open -> half-open when the dwell elapses.
    """

    def __init__(
        self,
        name: str,
        state_series: str = "",
        trips_series: str = "",
        *,
        metrics=None,
        spans=None,
        failure_threshold: int = 1,
        open_secs: float = 5.0,
        probe_successes: int = 1,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.name = name
        self.state_series = state_series
        self.trips_series = trips_series
        self.metrics = metrics
        self.spans = spans
        self.failure_threshold = max(1, int(failure_threshold))
        self.open_secs = float(open_secs)
        self.probe_successes = max(1, int(probe_successes))
        self.clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED  # guarded-by: _lock
        self._failures = 0  # guarded-by: _lock (consecutive)
        self._open_until = 0.0  # guarded-by: _lock
        self._probe_inflight = False  # guarded-by: _lock
        self._probe_ok = 0  # guarded-by: _lock
        self.trips = 0  # total open transitions (stats/REST)

    @property
    def state(self) -> str:
        with self._lock:
            return self._effective_state()

    def _effective_state(self) -> str:  # holds-lock: _lock
        # open dwell elapsing is observable without a transition call:
        # state reads must never report "open" past the probe due time
        if self._state == OPEN and self.clock() >= self._open_until:
            return HALF_OPEN
        return self._state

    def allow(self) -> bool:
        """May the caller take the fast path right now?"""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN and self.clock() >= self._open_until:
                self._transition(HALF_OPEN, reason="probe_due")
            if self._state == HALF_OPEN and not self._probe_inflight:
                self._probe_inflight = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            if self._state == CLOSED:
                self._failures = 0
                return
            self._probe_inflight = False
            self._probe_ok += 1
            if self.metrics is not None:
                self.metrics.inc("degrade.probe.ok")
            if self._probe_ok >= self.probe_successes:
                self._failures = 0
                self._transition(CLOSED, reason="probe_recovered")

    def record_failure(self, reason: str = "failure") -> None:
        with self._lock:
            if self._state in (HALF_OPEN, OPEN):
                # a failed probe (or a straggler failing while open)
                # restarts the dwell — no threshold accounting
                self._probe_inflight = False
                if self._state == HALF_OPEN and self.metrics is not None:
                    self.metrics.inc("degrade.probe.fail")
                self._open_until = self.clock() + self.open_secs
                self._transition(OPEN, reason=f"probe_{reason}")
                return
            self._failures += 1
            if self._failures >= self.failure_threshold:
                self._open_until = self.clock() + self.open_secs
                self.trips += 1
                if self.metrics is not None and self.trips_series:
                    self.metrics.inc(self.trips_series)
                self._transition(OPEN, reason=reason)

    def _transition(self, new: str, reason: str) -> None:  # holds-lock: _lock
        old, self._state = self._state, new
        if new != OPEN:
            self._probe_ok = 0 if new == HALF_OPEN else self._probe_ok
        if new == CLOSED:
            self._probe_ok = 0
        if old == new:
            return
        log.warning(
            "degrade[%s]: %s -> %s (%s)", self.name, old, new, reason
        )
        if self.metrics is not None and self.state_series:
            self.metrics.gauge_set(self.state_series, STATE_CODE[new])
        rec = self.spans
        if rec is not None:
            # span event: the causal record of WHY subsequent messages
            # take the slow path (queryable next to their deliver spans)
            sp = rec.start(
                "degrade.transition",
                attrs={
                    "path": self.name,
                    "from": old,
                    "to": new,
                    "reason": reason,
                },
            )
            rec.finish(sp)

    def force(self, state: str, open_remaining_s: float = 0.0) -> None:
        """Restore-time entry (rolling upgrade): re-enter a persisted
        state without replaying the failures that caused it."""
        with self._lock:
            if state == OPEN:
                self._open_until = self.clock() + max(0.0, open_remaining_s)
                self._transition(OPEN, reason="restored")
            elif state == HALF_OPEN:
                # resume as open-with-elapsed-dwell: the next allow()
                # probes immediately (same observable behavior, no
                # probe-inflight token leaks across the restart)
                self._open_until = self.clock()
                self._transition(OPEN, reason="restored")
            else:
                self._failures = 0
                self._transition(CLOSED, reason="restored")

    def to_json(self) -> Dict:
        with self._lock:
            return {
                "state": self._effective_state(),
                "trips": self.trips,
                "open_remaining_s": max(0.0, self._open_until - self.clock())
                if self._state == OPEN
                else 0.0,
            }


class DegradeController:
    """The broker's breaker set + shared retry policy.

    Paths:
    - ``device``: route/launch/readback failures. Open = whole batches
      serve from the CPU trie; ingest sheds past its queue bound.
    - ``cluster_send``: created per destination by the TCP bus via
      `cluster_breaker()` (one dead peer must not gate healthy ones);
      all share the cluster_send series.
    """

    def __init__(
        self,
        metrics=None,
        spans=None,
        *,
        max_retries: int = 2,
        backoff_base_s: float = 0.02,
        backoff_max_s: float = 2.0,
        jitter: float = 0.5,
        failure_threshold: int = 1,
        open_secs: float = 5.0,
        probe_successes: int = 1,
        shed_queue_batches: int = 8,
        clock: Callable[[], float] = time.monotonic,
        seed: int = 0,
    ):
        self.metrics = metrics
        self.spans = spans
        self.max_retries = max(0, int(max_retries))
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.jitter = float(jitter)
        self.shed_queue_batches = max(1, int(shed_queue_batches))
        self._clock = clock
        self._rng = random.Random(seed)
        self._mk = dict(
            metrics=metrics,
            spans=spans,
            failure_threshold=failure_threshold,
            open_secs=open_secs,
            probe_successes=probe_successes,
            clock=clock,
        )
        self.device = Breaker(
            "device",
            state_series="degrade.state.device",
            trips_series="degrade.trips.device",
            **self._mk,
        )
        self._cluster_lock = threading.Lock()
        self._cluster: Dict[str, Breaker] = {}  # guarded-by: _cluster_lock

    # -- retry policy -------------------------------------------------------
    def retry_delays(self) -> Iterator[float]:
        """Bounded exponential backoff + jitter: one delay per retry
        attempt (max_retries total). Each yield counts degrade.retries."""
        d = self.backoff_base_s
        for _ in range(self.max_retries):
            if self.metrics is not None:
                self.metrics.inc("degrade.retries")
            yield min(self.backoff_max_s, d) * (
                1.0 + self.jitter * self._rng.random()
            )
            d *= 2.0

    # -- cluster breakers ---------------------------------------------------
    def cluster_breaker(self, dst: str) -> Breaker:
        """Per-destination breaker (lazily created). All destinations
        share the cluster_send series: the gauge reports the most recent
        transition's state (any-path indicator), trips aggregate."""
        with self._cluster_lock:
            br = self._cluster.get(dst)
            if br is None:
                br = Breaker(
                    f"cluster_send:{dst}",
                    state_series="degrade.state.cluster_send",
                    trips_series="degrade.trips.cluster_send",
                    **self._mk,
                )
                self._cluster[dst] = br
            return br

    # -- rolling-upgrade persistence ---------------------------------------
    def snapshot(self) -> Dict:
        """Serializable breaker states (DurableState ships this across a
        drain/restart so a node resuming mid-degradation re-enters the
        correct state instead of re-learning it from live failures)."""
        with self._cluster_lock:
            cluster = {d: b.to_json() for d, b in self._cluster.items()}
        return {"device": self.device.to_json(), "cluster": cluster}

    def restore(self, data: Optional[Dict]) -> None:
        if not data:
            return
        dev = data.get("device") or {}
        if dev.get("state") in (OPEN, HALF_OPEN):
            self.device.force(
                dev["state"], float(dev.get("open_remaining_s", 0.0))
            )
        self.device.trips = int(dev.get("trips", self.device.trips))
        for dst, b in (data.get("cluster") or {}).items():
            if b.get("state") in (OPEN, HALF_OPEN):
                self.cluster_breaker(dst).force(
                    b["state"], float(b.get("open_remaining_s", 0.0))
                )

    def to_json(self) -> Dict:
        return self.snapshot()
