"""Delayed publish: $delayed/<seconds>/<real topic>.

Parity with the reference module (apps/emqx_modules/src/emqx_delayed.erl):
messages published to $delayed/N/t are intercepted on the 'message.publish'
hook, held for N seconds, then republished to t. Max delay capped; store is
a heap swept by `tick()` from the server loop (the reference uses a
mnesia-backed timer process).
"""

from __future__ import annotations

import heapq
import time
from typing import List, Optional, Tuple

from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.broker.message import Message

PREFIX = "$delayed/"
MAX_DELAY = 4294967  # seconds (reference cap)


class DelayedPublish:
    def __init__(
        self, broker, max_delay: int = MAX_DELAY, max_messages: int = 0
    ):
        self.broker = broker
        self.max_delay = max_delay
        self.max_messages = max_messages  # 0 = unlimited (reference default)
        self._heap: List[Tuple[float, int, Message]] = []
        self._seq = 0
        self.enabled = True
        self.dropped = 0

    def __len__(self) -> int:
        return len(self._heap)

    def intercept(self, msg: Optional[Message]):
        """'message.publish' fold callback: swallow $delayed messages."""
        if msg is None or not self.enabled or not msg.topic.startswith(PREFIX):
            return None  # keep acc
        rest = msg.topic[len(PREFIX) :]
        delay_s, sep, real_topic = rest.partition("/")
        try:
            delay = int(delay_s)
        except ValueError:
            delay = -1
        if not sep or delay < 0 or real_topic == "":
            return None  # malformed: treat as a normal topic
        delay = min(delay, self.max_delay)
        if self.max_messages and len(self._heap) >= self.max_messages:
            # store full: drop the delayed message (reference behavior when
            # max_delayed_messages is reached), still swallow the original
            self.dropped += 1
            return ("stop", None)
        import copy

        m = copy.copy(msg)
        m.topic = real_topic
        self._seq += 1
        # monotonic deadline: a forward wall-clock step must not fire
        # every delayed message at once (nor a backward one freeze them).
        # DurableState persists the REMAINING interval and rebases here
        # at restore (persistent_session.py).
        heapq.heappush(self._heap, (time.monotonic() + delay, self._seq, m))
        # stop the fold with None acc => broker.publish drops the original
        return ("stop", None)

    def tick(self, now: Optional[float] = None) -> int:
        """Publish all due messages; returns how many fired. `now` is a
        `time.monotonic()` value (tests patch it)."""
        now = time.monotonic() if now is None else now
        n = 0
        while self._heap and self._heap[0][0] <= now:
            _, _, m = heapq.heappop(self._heap)
            self.broker.publish(m)
            n += 1
        return n

    def pending(self) -> List[Tuple[float, Message]]:
        """[(monotonic due, msg)] — persistence converts to remaining
        intervals before writing (a raw monotonic stamp is meaningless
        in another process)."""
        return [(due, m) for due, _, m in sorted(self._heap)]

    def load(self, due: float, msg: Message) -> bool:
        """Direct insert for durable-state restore (`due` is a
        `time.monotonic()` deadline); honors the cap."""
        if self.max_messages and len(self._heap) >= self.max_messages:
            self.dropped += 1
            return False
        self._seq += 1
        heapq.heappush(self._heap, (due, self._seq, msg))
        return True

    def attach(self, hooks: Hooks) -> None:
        hooks.add("message.publish", self.intercept, priority=200)
