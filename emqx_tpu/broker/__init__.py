"""Host-side broker data plane: pub/sub kernel, sessions, dispatch."""
