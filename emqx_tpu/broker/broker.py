"""The pub/sub kernel: subscribe/unsubscribe/publish/dispatch.

Parity with the reference kernel (apps/emqx/src/emqx_broker.erl):
- subscribe/unsubscribe maintain the subscriber registry + route table
  (emqx_broker.erl:127-160 ETS inserts + :441-454 route add)
- publish runs the 'message.publish' fold, matches routes, and dispatches
  to local subscribers (:204-215 publish, :505-530 do_dispatch)
- publish_batch is the TPU-era addition: many topics matched in one device
  kernel, then fanned out (the reference has no batch path — its hot loop
  is per-message, which is exactly what this design replaces)

Dispatch hands (session, opts, msg) triples to each subscriber's channel via
the session's registered deliver callback. Shared-subscription groups
($share/g/t) are delegated to SharedSub.
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from emqx_tpu.broker.hooks import Hooks, default_hooks
from emqx_tpu.broker.message import Message
from emqx_tpu.broker.metrics import Metrics
from emqx_tpu.broker.router import Router
from emqx_tpu.broker.shared_sub import SharedSub
from emqx_tpu.mqtt import packet as pkt
from emqx_tpu.observe.spans import TRACE_HEADER
from emqx_tpu.ops import topics as T
from emqx_tpu.utils.tracepoints import tp

# deliverer: called with (msg, subopts); returns True if accepted
Deliverer = Callable[[Message, pkt.SubOpts], None]

_dispatch_pool_inst = None


def dispatch_pool():
    """Process-wide executor for device route launches (one device per
    process). BOUNDED and dedicated: the default asyncio executor is
    shared with every other run_in_executor caller (config writes, DNS,
    bench driver plumbing), so device launches could queue behind
    unrelated blocking work — and an unbounded shared queue is exactly
    the backlog shape the r02/r04 bench notes flagged. Two workers are
    the double-buffer: batch N+1's tokenize/launch phase runs on the
    second worker while batch N's worker blocks in its readback."""
    global _dispatch_pool_inst
    if _dispatch_pool_inst is None:
        from concurrent.futures import ThreadPoolExecutor

        _dispatch_pool_inst = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="tpu-dispatch"
        )
    return _dispatch_pool_inst


class Subscriber:
    __slots__ = (
        "sid", "deliver", "opts", "client_id", "slot", "filter",
        "semantic",
    )

    def __init__(self, sid: str, client_id: str, deliver: Deliverer, opts: pkt.SubOpts):
        self.sid = sid
        self.client_id = client_id
        self.deliver = deliver
        self.opts = opts
        self.slot = -1  # device bitmap slot (non-shared subs only)
        self.filter = ""  # the real (share-stripped) subscription filter
        # embedding-filtered subscription (docs/semantic_routing.md):
        # the slot lives in the SemanticTable, NOT the subscriber
        # table — delivery requires topic AND similarity
        self.semantic = False


class PendingDispatch:
    """A launched-but-unsettled batch dispatch (adispatch_begin).

    `ready`: side-effect-free future resolving when the device round
    trip completes (never triggers fan-out — safe to race/poll).
    `complete()`: coroutine performing the host fan-out + returning
    per-message delivery counts; callers invoke it in launch order.
    Awaiting the object is shorthand for awaiting complete()."""

    __slots__ = ("ready", "_complete")

    def __init__(self, ready, complete):
        self.ready = ready
        self._complete = complete

    def complete(self):
        return self._complete()

    def __await__(self):
        return self._complete().__await__()


class Broker:
    def __init__(
        self,
        router: Optional[Router] = None,
        hooks: Optional[Hooks] = None,
        metrics: Optional[Metrics] = None,
    ):
        # NOT `router or Router()`: Router defines __len__, so an EMPTY
        # router is falsy and would be silently swapped for a default one
        self.router = router if router is not None else Router()
        self.hooks = hooks or default_hooks
        self.metrics = metrics or Metrics()
        # filter -> {sid -> Subscriber}
        self._subs: Dict[str, Dict[str, Subscriber]] = {}
        self.shared = SharedSub()
        # device fan-out state: every non-shared Subscriber entry gets a
        # dense bitmap slot; (filter id, slot) rides to the device so the
        # route_step kernel resolves topic -> subscriber bits directly
        # (emqx_broker.erl:505-530 do_dispatch, as one gather+OR)
        from emqx_tpu.models.router_model import GroupTable, SubscriberTable

        # router.sub_table policy (docs/serving_pipeline.md): the CSR
        # representation serves through the compact readback contract,
        # so fanout_compact=False pins the dense matrix (the fallback)
        mc = self.router.matcher_config
        self.subtab = SubscriberTable(
            mode=(
                getattr(mc, "sub_table", "auto")
                if getattr(mc, "fanout_compact", True)
                else "dense"
            ),
        )
        # running plain-subscription count: subscription_count() used to
        # RECOMPUTE sum(len(entry)) per subscribe/unsubscribe, turning a
        # million-connection subscribe storm into O(N^2) gauge upkeep
        self._plain_subs = 0
        # $share groups mirrored as device lane segments so the kernel
        # resolves the member pick too (emqx_shared_sub.erl:234-285)
        self.grouptab = GroupTable()
        self._slot_subs: List[Optional[Subscriber]] = []
        self._free_slots: List[int] = []
        self._device = None  # lazy DeviceRouter
        self.mesh = None  # jax Mesh => SPMD serving (set by app/tests)
        # cluster mesh-slice label (ClusterNode.attach_mesh_slice):
        # stamped onto router.device_step spans by the mesh engine
        self.shard_label = None
        self.ingest = None  # BatchIngest, attached by the app
        # RetainedStormFeed (broker/retained_feed.py), attached by the
        # app: pending wildcard-subscribe replay storms ride the next
        # device launch via the fused kernel instead of paying their own
        self.retained_feed = None
        # SpanRecorder (observe/spans.py), attached by the app/tests:
        # causal span tracing across the batch boundary. None = off; the
        # hot path pays one attribute check per publish
        self.spans = None
        # ClusterNode, attached by the app when cluster.enable: routes
        # replicate on first/last subscriber, publishes forward to remote
        # route owners (emqx_broker.erl:278-293 forward regime)
        self.cluster = None
        # DegradeController (broker/degrade.py), attached by the app:
        # device-path circuit breaker + bounded retry policy. None =
        # legacy behavior (a failed launch fails its batch's publishes)
        self.degrade = None
        # SessionStore (broker/session_store.py), attached by the app
        # when session.device_store: pending inflight writes + QoS
        # retry/expiry sweeps ride serving launches as the fused
        # session-ack stage (no extra launch or readback per batch)
        self.session_store = None
        # SemanticRouting (broker/semantic.py), attached by the app
        # when semantic.enable: embedding-filter subscriptions ride the
        # serving launch as a fused similarity matmul; None = the
        # semantic stage never traces (docs/semantic_routing.md)
        self.semantic = None
        # RuleEngine's device-predicate seam (rules/engine.py
        # attach_device): compiled WHERE masks evaluate inside the
        # serving launch and fire at settle; None = hook-path rules
        self.rule_hook = None

    # -- subscribe side ---------------------------------------------------
    def subscribe(
        self,
        sid: str,
        client_id: str,
        filter_: str,
        opts: pkt.SubOpts,
        deliver: Deliverer,
        embedding=None,
        sem_threshold=None,
    ) -> None:
        """`embedding`/`sem_threshold`: an optional embedding filter
        (docs/semantic_routing.md) — the subscription then delivers on
        topic match AND similarity (its slot lives in the semantic
        table, not the fan-out table). Ignored (plain subscribe) when
        no SemanticRouting is attached or the filter is $shared."""
        group, real = T.parse_share(filter_)
        sub = Subscriber(sid, client_id, deliver, opts)
        sub.filter = real
        if embedding is not None and (
            self.semantic is None or group is not None
        ):
            # no semantic plane (or a $share filter, which resolves by
            # group pick, not slots): degrade to a plain subscription
            self.metrics.inc("semantic.subscribe.rejected")
            embedding = None
        if group is not None:
            # one route ref per group (matched by delete on group-empty)
            if self.shared.subscribe(group, real, sub):
                rk = self.shared.route_filter(group, real)
                self.router.add_route(rk)
                if self.cluster is not None:
                    self.cluster._replicate_add(rk)
                    self.cluster.shared_join(real, group)
            fid = self.router.filter_id(real)
            if fid is not None:
                gid = self.grouptab.ensure_group(fid, real, group)
                g = self.shared.group(real, group)
                self.grouptab.set_len(gid, len(g.members) if g else 0)
        else:
            entry = self._subs.setdefault(real, {})
            prev = entry.get(sid)
            first = not entry
            entry[sid] = sub
            if prev is None:
                self._plain_subs += 1
            fid = (
                self.router.add_route(real)
                if first
                else None
            )
            if first and self.cluster is not None:
                self.cluster._replicate_add(real)
            if prev is not None:
                # re-subscribe with fresh opts: keep the slot, swap the sub
                sub.slot = prev.slot
                self._slot_subs[sub.slot] = sub
            else:
                sub.slot = self._alloc_slot(sub)
            if fid is None:
                # route already existed: resolve its id (one probe)
                fid = self.router.filter_id(real)
            if embedding is not None:
                # embedding-filtered subscription: the slot binds into
                # the semantic table (topic scope = this filter's fid;
                # '#' scopes degenerate to unscoped similarity-only)
                sub.semantic = True
                if prev is not None and not prev.semantic:
                    if fid is not None:
                        self.subtab.remove(fid, sub.slot)
                th = (
                    self.semantic.default_threshold
                    if sem_threshold is None
                    else float(sem_threshold)
                )
                self.semantic.attach(
                    sid, sub.slot, embedding, th,
                    fid=-1 if fid is None else fid, scope=real,
                )
            else:
                if prev is not None and prev.semantic:
                    # the re-subscribe dropped the embedding filter:
                    # back to plain fan-out
                    self.semantic.detach(sub.slot)
                if prev is None or prev.semantic:
                    if fid is not None:
                        self.subtab.add(fid, sub.slot)
        self.metrics.gauge_set("subscriptions.count", self.subscription_count())

    def unsubscribe(self, sid: str, filter_: str) -> bool:
        group, real = T.parse_share(filter_)
        if group is not None:
            fid = self.router.filter_id(real)
            removed, empty = self.shared.unsubscribe(group, real, sid)
            if empty:
                if fid is not None:
                    self.grouptab.drop_group(fid, real, group)
                rk = self.shared.route_filter(group, real)
                self.router.delete_route(rk)
                if self.cluster is not None:
                    self.cluster._replicate_delete(rk)
                    self.cluster.shared_leave(real, group)
            elif removed and fid is not None:
                gid = self.grouptab.gid_of(real, group)
                g = self.shared.group(real, group)
                if gid is not None and g is not None:
                    self.grouptab.set_len(gid, len(g.members))
                    # a member leaving shifts indices: re-derive the pin
                    # from the sid so it stays on the same live member
                    self.grouptab.repin(gid, g.members.keys(), g.sticky_sid)
            return removed
        entry = self._subs.get(real)
        if not entry or sid not in entry:
            return False
        sub = entry.pop(sid)
        self._plain_subs -= 1
        if sub.slot >= 0:
            if sub.semantic and self.semantic is not None:
                self.semantic.detach(sub.slot)
            else:
                fid = self.router.filter_id(real)
                if fid is not None:
                    self.subtab.remove(fid, sub.slot)
            self._free_slot(sub.slot)
        if not entry:
            del self._subs[real]
            self.router.delete_route(real)
            if self.cluster is not None:
                self.cluster._replicate_delete(real)
        self.metrics.gauge_set("subscriptions.count", self.subscription_count())
        return True

    def _alloc_slot(self, sub: Subscriber) -> int:
        if self._free_slots:
            slot = self._free_slots.pop()
            self._slot_subs[slot] = sub
            return slot
        self._slot_subs.append(sub)
        return len(self._slot_subs) - 1

    def _free_slot(self, slot: int) -> None:
        self._slot_subs[slot] = None
        self._free_slots.append(slot)

    def subscription_count(self) -> int:
        return self._plain_subs + self.shared.count()

    def subscriptions(self) -> List[Tuple[str, str, pkt.SubOpts]]:
        out = []
        for f, entry in self._subs.items():
            for sub in entry.values():
                out.append((sub.client_id, f, sub.opts))
        out.extend(self.shared.subscriptions())
        return out

    # -- publish side -----------------------------------------------------
    def publish(self, msg: Message) -> int:
        """Route + dispatch one message; returns delivery count."""
        rec = self.spans
        sp = rec.publish_begin(msg) if rec is not None else None
        msg = self.hooks.run_fold("message.publish", (), msg)
        n = self._publish_folded(msg)
        if sp is not None:
            rec.finish_span(sp, n)
        return n

    async def apublish(self, msg: Message) -> int:
        """Async `publish` for the connection path: awaits async hooks
        (exhook sidecars) so a slow extension suspends only the publishing
        client's task, not the event loop. When a BatchIngest is attached,
        the folded message rides the adaptive batch window onto the device
        route path instead of a per-message CPU match."""
        r = await self.apublish_enqueue(msg)
        return r if isinstance(r, int) else await r

    async def apublish_enqueue(self, msg: Message):
        """Pipelined publish: fold + enqueue WITHOUT awaiting dispatch.

        Returns either an int (dispatched inline / dropped) or an
        asyncio.Future resolving to the delivery count when the batch
        flushes. This is what lets a connection keep parsing subsequent
        frames while earlier publishes ride the batch window — the analog
        of the reference's active-N=100 socket pipeline
        (emqx_connection.erl:125), without which one connection could never
        have more than one message in a batch.
        """
        rec = self.spans
        # span head BEFORE the fold: the publish span covers hook time,
        # and the stamped context header rides into exhook sidecar calls
        sp = rec.publish_begin(msg) if rec is not None else None
        rh = self.rule_hook
        if rh is not None and rh.device_active():
            ing0 = self.ingest
            if ing0 is not None and ing0.running:
                # device-compiled rule WHEREs defer to settle time: the
                # batch evaluates them inside the serving launch (the
                # hook-path evaluator skips marked messages)
                msg.headers["_batch_rules"] = True
        msg = await self.hooks.arun_fold("message.publish", (), msg)
        if msg is None or msg.headers.get("allow_publish") is False:
            self.metrics.inc("messages.dropped")
            if sp is not None:
                rec.finish_span(sp, 0, status="error")
            return 0
        ing = self.ingest
        if ing is not None and ing.running:
            # the publish span settles inside BatchIngest._finish (by
            # context header) when the batch dispatch completes
            return ing.enqueue(msg)
        n = self._dispatch_routed(msg)
        if sp is not None:
            rec.finish_span(sp, n)
        return n

    def _publish_folded(self, msg: Optional[Message]) -> int:
        """Shared tail of publish/apublish after the message.publish fold."""
        if msg is None or msg.headers.get("allow_publish") is False:
            self.metrics.inc("messages.dropped")
            return 0
        return self._dispatch_routed(msg)

    def _dispatch_routed(self, msg: Message, forward: bool = True) -> int:
        """Local dispatch + cluster forward. `forward=False` marks the
        RECEIVING half of a cluster forward — it must never re-forward,
        or every forwarded batch cascades node-to-node forever."""
        rec = self.spans
        t_ns = (
            rec.now_ns()
            if rec is not None and TRACE_HEADER in msg.headers
            else 0
        )
        n = self._route_dispatch(msg, self.router.match(msg.topic))
        if t_ns:
            rec.deliver(msg, n, start_ns=t_ns)
        if forward and self.cluster is not None:
            n += self.cluster.forward_batch_remote([msg])[0]
        if n == 0:
            self.hooks.run("message.dropped", msg, "no_subscribers")
            self.metrics.inc("messages.dropped.no_subscribers")
        return n

    def publish_batch(self, msgs: Sequence[Message]) -> int:
        """Batch publish: one TPU kernel for all topics, then fan out."""
        rh = self.rule_hook
        defer = rh is not None and rh.device_active()
        msgs2: List[Message] = []
        for m in msgs:
            if defer:
                m.headers["_batch_rules"] = True
            m = self.hooks.run_fold("message.publish", (), m)
            if m is not None and m.headers.get("allow_publish") is not False:
                msgs2.append(m)
        return sum(self.dispatch_batch_folded(msgs2))

    def dispatch_batch_folded(
        self, msgs: Sequence[Message], forward: bool = True
    ) -> List[int]:
        """Route + dispatch already-folded messages as one device step.

        The full flagship pipeline: tokenize + NFA match + bitmap fan-out in
        one jitted route_step, then host delivery straight from subscriber
        bits. Rows the kernel flags (too deep / overflow) fall back to the
        authoritative CPU path per row; batches too small to amortize a
        dispatch skip the device entirely. `forward=False` = receiving
        half of a cluster forward (never re-forward).
        """
        r = self.router
        if not (r.enable_tpu and len(msgs) >= r.min_tpu_batch):
            return self._dispatch_cpu_batch(msgs, forward)
        deg = self.degrade
        if deg is not None and not deg.device.allow():
            # breaker open: degraded serving from the authoritative CPU
            # trie at batch granularity (docs/robustness.md)
            self.metrics.inc("degrade.fallback.batches")
            tp("dispatch.degraded", n=len(msgs))
            return self._dispatch_cpu_batch(msgs, forward)
        dev = self._device_router()
        rec = self.spans
        t_launch = rec.now_ns() if rec is not None else 0
        try:
            results = dev.route(
                # topic_key(): zero-copy ingest — slab-backed messages
                # hand the tokenizer a TopicRef into the fabric read
                # buffer instead of paying a str decode per row
                [m.topic_key() for m in msgs], self._client_hashes(msgs),
                embeds=self._embeds(msgs), rules=self._rule_batch(msgs),
            )
        except Exception:  # noqa: BLE001 — degrade, don't fail the batch
            if deg is None:
                raise
            # sync callers get no backoff train (they may hold the event
            # loop); the async serving path owns the retry ladder
            deg.device.record_failure("route")
            self.metrics.inc("degrade.fallback.batches")
            tp("dispatch.degraded", n=len(msgs))
            return self._dispatch_cpu_batch(msgs, forward)
        if deg is not None:
            deg.device.record_success()
        dsp = None
        if rec is not None:
            # sync path has no ingest batch span: the device-step span
            # stands alone, linked to the sampled publishes directly
            dsp = rec.device_step(
                None, len(msgs), results, t_launch,
                links=rec.publish_links(msgs),
                extra=dev.span_attrs(),
            )
        return self._dispatch_device_results(
            msgs, results, forward, device_span=dsp
        )

    def _dispatch_cpu_batch(
        self, msgs: Sequence[Message], forward: bool = True
    ) -> List[int]:
        """The authoritative CPU slow path for a whole batch: per-message
        trie match + host fan-out, remote fan-out still batched per
        destination node. This is both the small-batch branch and the
        degradation target when the device path is broken or its breaker
        is open — it must never itself touch the device. Deferred
        device-compiled rules fire here through the vectorized HOST
        evaluator (the degrade ladder's middle rung); semantic
        recipients resolve per message inside `_route_dispatch` via the
        host twin."""
        if self.rule_hook is not None:
            self.rule_hook.fire_settled(msgs)
        if forward and self.cluster is not None and len(msgs) > 1:
            # keep remote fan-out batched per destination node even
            # on the CPU branch (one forward_batch per node, not one
            # per message per node)
            fwd = self.cluster.forward_batch_remote(msgs)
            rec = self.spans
            out = []
            for i, m in enumerate(msgs):
                t_ns = (
                    rec.now_ns()
                    if rec is not None and TRACE_HEADER in m.headers
                    else 0
                )
                n = self._route_dispatch(
                    m, self.router.match(m.topic)
                )
                if t_ns:
                    rec.deliver(m, n, start_ns=t_ns)
                n += fwd[i]
                if n == 0:
                    self.hooks.run("message.dropped", m, "no_subscribers")
                    self.metrics.inc("messages.dropped.no_subscribers")
                out.append(n)
            return out
        return [self._dispatch_routed(m, forward) for m in msgs]

    async def adispatch_batch_folded(
        self, msgs: Sequence[Message], forward: bool = True
    ) -> List[int]:
        """`dispatch_batch_folded` with the kernel launch + readback (and
        any jit recompile, which can take tens of seconds on a real chip)
        offloaded to an executor thread so the event loop keeps serving
        every other connection. Table packing/upload and delivery stay on
        the loop thread — they touch mutable broker state."""
        return await self.adispatch_begin(msgs, forward)

    def adispatch_begin(
        self, msgs: Sequence[Message], forward: bool = True,
        batch_span=None,
    ) -> "PendingDispatch":
        """Launch the device dispatch for a batch NOW (table snapshot +
        executor kernel submit) and return a PendingDispatch. This is
        the ingest pipeline's seam: batch N+1's upload+launch overlaps
        batch N's readback round-trip (the dominant wall on a tunneled
        chip; on real hardware it overlaps host fan-out with device
        compute).

        The host FAN-OUT runs only inside `complete()` (equivalently:
        awaiting the object) — NEVER autonomously when the device work
        finishes — so callers settling batches in launch (FIFO) order
        preserve MQTT's per-publisher delivery ordering across batches.
        `ready` is a side-effect-free future signalling that the device
        round-trip finished (pipeline pacing only)."""
        loop = asyncio.get_running_loop()
        r = self.router
        deg = self.degrade

        def _cpu_pending(degraded: bool = False):
            ready = loop.create_future()
            ready.set_result(None)
            if degraded:
                self.metrics.inc("degrade.fallback.batches")
                tp("dispatch.degraded", n=len(msgs))

            async def _cpu():
                # CPU batches defer dispatch to settle time too: a small
                # batch settling before an in-flight device batch would
                # invert cross-batch delivery order. A DEGRADED batch
                # must bypass the device re-entry inside
                # dispatch_batch_folded, not just prefer CPU.
                if degraded:
                    if batch_span is not None:
                        batch_span.attrs["degraded"] = True
                    return self._dispatch_cpu_batch(msgs, forward)
                return self.dispatch_batch_folded(msgs, forward)

            return PendingDispatch(ready, _cpu)

        if not (r.enable_tpu and len(msgs) >= r.min_tpu_batch):
            return _cpu_pending()
        if deg is not None and not deg.device.allow():
            # breaker open: the whole batch serves from the CPU trie
            # (half-open probes re-enter here one batch at a time)
            return _cpu_pending(degraded=True)
        dev = self._device_router()
        t_prep = time.perf_counter()
        try:
            args = dev.prepare()
        except Exception:  # noqa: BLE001 — no good epoch: degrade
            if deg is None:
                raise
            deg.device.record_failure("delta_sync")
            return _cpu_pending(degraded=True)
        # waterfall `prepare` (observe/profiler.py): table snapshot +
        # upload cost this launch paid before any device work
        self.metrics.observe(
            "profile.stage.prepare.seconds", time.perf_counter() - t_prep
        )
        feed = self.retained_feed
        storm = None
        if feed is not None and dev.supports_retained_fusion:
            # pending wildcard-subscribe replays ride THIS launch: the
            # fused kernel answers them in the same program + readback
            # (fused_route_retained_step single-device; dist_fused_step
            # on the mesh engine, chunk rows scanning sharded over 'dp')
            storm = feed.take_job()
        store = self.session_store
        rider = None
        if store is not None and storm is None and getattr(
            dev, "supports_session_fusion", False
        ):
            # pending session-table writes (+ a requested retry/expiry
            # sweep) fuse into THIS launch as the session-ack stage —
            # ack batches never pay their own device launch, and the
            # sweep lists ride the same coalesced readback
            rider = store.take_rider()
            if rider is not None and batch_span is not None:
                batch_span.attrs["session.rider.rows"] = rider.rows
                if rider.sweep_k:
                    batch_span.attrs["session.sweep"] = True
        rec = self.spans
        t_launch = rec.now_ns() if rec is not None else 0
        # topic_key(): slab-backed messages defer str decode — the
        # tokenizer gathers their bytes straight from the fabric slab
        topics = [m.topic_key() for m in msgs]
        hashes = self._client_hashes(msgs)
        embeds = self._embeds(msgs)
        rules = self._rule_batch(msgs)
        fut = loop.run_in_executor(
            dispatch_pool(),
            dev.route_prepared,
            args,
            topics,
            hashes,
            storm,
            rider,
            embeds,
            rules,
        )
        if storm is not None:
            feed.attach(storm, fut)

        async def _complete():
            srd = rider
            try:
                results = await fut
            except Exception:  # noqa: BLE001 — the retry ladder owns it
                if deg is None:
                    if srd is not None:
                        store.abort(srd)
                    raise
                results = None
            if results is None and srd is not None:
                # the failed launch carried the session rider: nothing
                # is lost (host arrays are authoritative) — its writes
                # stay queued and ride a later launch or the segment
                # scatter path; retries relaunch bare
                store.abort(srd)
                srd = None
            if results is None:
                # bounded exponential backoff + jitter, then degrade:
                # each retry re-prepares (the failure may have been a
                # torn sync; rollback serves the last good epoch) and
                # relaunches WITHOUT the storm (its waiters already fell
                # back to the CPU walk via feed.attach's done-callback)
                for delay in deg.retry_delays():
                    await asyncio.sleep(delay)
                    try:
                        args2 = dev.prepare()
                        results = await loop.run_in_executor(
                            dispatch_pool(),
                            dev.route_prepared,
                            args2,
                            topics,
                            hashes,
                            None,
                            None,
                            embeds,
                            rules,
                        )
                        break
                    except Exception:  # noqa: BLE001 — keep retrying
                        results = None
            if results is None:
                # retries exhausted: trip the breaker, serve this batch
                # from the CPU trie — the publishes SUCCEED (identical
                # recipient sets, slower path), they don't fail
                deg.device.record_failure("launch")
                self.metrics.inc("degrade.fallback.batches")
                tp("dispatch.degraded", n=len(msgs))
                if batch_span is not None:
                    batch_span.attrs["degraded"] = True
                return self._dispatch_cpu_batch(msgs, forward)
            if deg is not None:
                deg.device.record_success()
            if srd is not None and results.session is not None:
                # adopt the updated device mirror + act on the sweep
                # (back on the loop — the single-writer discipline)
                store.commit(srd, results.session)
            if storm is not None:
                # no-op when the storm already failed over (retry path)
                feed.resolve(storm, results.retained)
            dsp = None
            if rec is not None:
                # the batch span (ingest fan-in) parents the device-step
                # span; batch-less callers get a standalone span linked
                # straight to the sampled publishes
                dsp = rec.device_step(
                    batch_span, len(msgs), results, t_launch,
                    links=rec.publish_links(msgs)
                    if batch_span is None
                    else (),
                    extra=dev.span_attrs(),
                )
            # waterfall `host_dispatch`: the settle-time fan-out of this
            # device batch (delivery resolution + writes)
            t_hd = time.perf_counter()
            res = self._dispatch_device_results(
                msgs, results, forward, device_span=dsp
            )
            self.metrics.observe(
                "profile.stage.host_dispatch.seconds",
                time.perf_counter() - t_hd,
            )
            return res

        return PendingDispatch(fut, _complete)

    def _device_router(self):
        if self._device is None:
            from emqx_tpu.models.router_model import (
                DeviceRouter,
                MeshServingRouter,
            )

            # mesh set => the scale-out engine: sharded table mirrors,
            # SPMD dist step, fused retained storms over the mesh
            cls = DeviceRouter if self.mesh is None else MeshServingRouter
            self._device = cls(
                self.router.index,
                self.subtab,
                self.router.matcher_config,
                grouptab=self.grouptab,
                share_strategy=self.shared.strategy,
                mesh=self.mesh,
                metrics=self.metrics,
                semtab=(
                    self.semantic.table
                    if self.semantic is not None
                    else None
                ),
            )
            if self.mesh is not None and self.shard_label:
                self._device.shard_label = self.shard_label
        return self._device

    def _embeds(self, msgs):
        """Per-message query embeddings for the fused semantic stage —
        None (and zero per-row cost) when no semantic plane is live."""
        sem = self.semantic
        if sem is None or not len(sem.table):
            return None
        return sem.embed_batch(msgs)

    def _rule_batch(self, msgs):
        """Compiled rule programs + the batch's feature matrix for the
        in-launch WHERE masks — None when no rule compiled."""
        rh = self.rule_hook
        if rh is None:
            return None
        return rh.device_progs(msgs)

    def _client_hashes(self, msgs):
        """Publisher-id hashes for the device $share pick — skipped
        entirely when no groups exist or the strategy doesn't use them."""
        if not len(self.grouptab) or self.shared.strategy != "hash_clientid":
            return None
        from emqx_tpu.broker.shared_sub import stable_hash

        return [stable_hash(m.from_client) for m in msgs]

    def _dispatch_device_results(
        self, msgs, results, forward: bool = True, device_span=None
    ) -> List[int]:
        """Fan one routed batch out to local subscribers.

        `results` is a `RouteResult`. On the compact path
        (`results.slots`) non-overflow rows dispatch straight from their
        slot-id lists — zero `unpackbits` — while overflow rows decode
        the dense rows of the masked second transfer; with compaction
        off every row decodes `results.bitmaps`. The match/fid memos are
        PER BATCH: the same (topic, filter) staleness re-verify and the
        same fid -> (name, has_groups) resolution used to repeat once
        per delivery."""
        matched, flags = results.matched, results.flags
        picks = results.picks
        r = self.router
        # deferred device-compiled rules fire FIRST (reference order:
        # rules run in the publish fold, before dispatch) — with the
        # in-launch masks when the batch carried them, else the host
        # evaluator ladder (rules/engine.fire_settled)
        if self.rule_hook is not None:
            self.rule_hook.fire_settled(msgs, masks=results.rule_masks)
        # semantic plane live for this batch: winner slots are already
        # unioned into the compact rows; rows only need the host-side
        # dedup net (mesh shards can union the same slot twice) and the
        # flight-recorder series
        sem = results.sem_count is not None
        if sem:
            hits = int(np.asarray(results.sem_count).sum())
            if hits:
                self.metrics.inc("semantic.hits", hits)
            topk = (
                self.semantic.table.topk
                if self.semantic is not None
                else 0
            )
            if topk:
                trunc = int(
                    np.count_nonzero(
                        np.asarray(results.sem_count) > topk
                    )
                )
                if trunc:
                    self.metrics.inc("semantic.topk.truncated", trunc)
        fwd = (
            self.cluster.forward_batch_remote(msgs)
            if forward and self.cluster is not None
            else None
        )
        out: List[int] = []
        fell_back = 0
        touched_gids: set = set()
        match_memo: Dict[Tuple[str, str], bool] = {}
        fid_memo: Dict[int, Tuple[Optional[str], bool]] = {}
        compact = results.slots is not None
        rec = self.spans
        # batch-level fan-out prep (docs/protocol_plane.md): ONE
        # .tolist() per device output matrix up front — the per-message
        # loop below then runs on plain ints, with per-row metric
        # observes batched into `fanouts` at the end. The old per-row
        # numpy mask/filter chains were a top per-message dispatch cost.
        flags_l = np.asarray(flags).tolist()
        slots_ll = results.slots.tolist() if compact else None
        ovf_l = results.overflow.tolist() if compact else None
        # matched filter-id rows only matter when shared groups exist
        # AND the device didn't already resolve the picks
        need_fids = picks is None and bool(self.shared._table)
        matched_l = matched.tolist() if need_fids else None
        fanouts: List[int] = []
        for i, m in enumerate(msgs):
            t_ns = (
                rec.now_ns()
                if rec is not None and TRACE_HEADER in m.headers
                else 0
            )
            if flags_l[i]:
                fell_back += 1
                tp("dispatch.fallback", topic=m.topic)
                n = self._route_dispatch(m, r.match(m.topic))
            else:
                msg_picks = (
                    (picks[0][i], picks[1][i]) if picks is not None else None
                )
                if compact and not ovf_l[i]:
                    # -1 pads skip inside the dispatch loop
                    bits, slots = None, slots_ll[i]
                elif compact:
                    bits = results.dense_rows[results.dense_index[i]]
                    # semantic winners live in the device slot row (the
                    # dense fallback covers only the TOPIC fan-out):
                    # union them back in — dup topic slots dedup below
                    slots = slots_ll[i] if sem else None
                else:
                    bits, slots = results.bitmaps[i], None
                # matched rows are SPARSE (-1 holes between engines)
                fids = (
                    [f for f in matched_l[i] if f >= 0]
                    if matched_l is not None
                    else ()
                )
                n = self._dispatch_row(
                    m, bits, fids, msg_picks, touched_gids,
                    slots=slots, match_memo=match_memo, fid_memo=fid_memo,
                    stats=fanouts, dedup=sem,
                )
            if t_ns:
                rec.deliver(
                    m, n, start_ns=t_ns, device_span=device_span,
                    fallback=bool(flags_l[i]),
                )
            if fwd is not None:
                n += fwd[i]
            if n == 0:
                self.hooks.run("message.dropped", m, "no_subscribers")
                self.metrics.inc("messages.dropped.no_subscribers")
            out.append(n)
        if fanouts:
            # batched flight-recorder upkeep: same series, one lock
            self.metrics.inc("messages.received", len(fanouts))
            self.metrics.observe_many("dispatch.fanout", fanouts)
            delivered = sum(fanouts)
            if delivered:
                self.metrics.inc("messages.delivered", delivered)
        if touched_gids:
            self._sync_group_counters(touched_gids)
        if fell_back:
            self.metrics.inc("messages.routed.device_fallback", fell_back)
        self.metrics.inc("messages.routed.device", len(msgs) - fell_back)
        tp("dispatch.batch", n=len(msgs), fallback=fell_back)
        return out

    def _dispatch_row(  # readback-site
        self, msg: Message, bits: Optional[np.ndarray], fids, picks=None,
        touched_gids: Optional[set] = None, *, slots=None,
        match_memo: Optional[Dict] = None,
        fid_memo: Optional[Dict] = None, stats: Optional[List] = None,
        dedup: bool = False,
    ) -> int:
        """Deliver one routed message from its device outputs: subscriber
        slot list (compact path) or bitmap (dense path) -> plain subs;
        matched filter ids -> shared groups.
        When `picks` is given ((gids, idxs) from the device $share pick),
        group delivery goes straight to the picked member with host-side
        failover only; otherwise the host runs the full pick.
        `slots` may be a plain int list (batch callers pre-.tolist() the
        whole slot matrix; -1 pads are skipped here) — with `stats`
        given, the fan-out lands in it and the per-row metric calls are
        batched by the caller instead. `bits` AND `slots` together =
        the semantic overflow contract: the dense row carries the topic
        fan-out, the slot list carries the device row's semantic
        winners, and `dedup` guards double delivery (also set for mesh
        batches, where two 'tp' shards can emit the same slot)."""
        if stats is None:
            self.metrics.inc("messages.received")
        if match_memo is None:
            match_memo = {}
        if fid_memo is None:
            fid_memo = {}
        n = 0
        topic = msg.topic
        if bits is not None:
            # dense decode. ascontiguousarray: readback rows can be
            # strided (axon backend / fancy-indexed fallback rows) and
            # ndarray.view raises on non-contiguous buffers
            if not bits.flags.c_contiguous:
                bits = np.ascontiguousarray(bits)
            dense = np.nonzero(
                np.unpackbits(bits.view(np.uint8), bitorder="little")
            )[0].tolist()
            if slots is None:
                slots = dense
            else:
                # dense topic fan-out + the device row's semantic
                # winners (overflow rows on the semantic plane)
                if not isinstance(slots, list):
                    slots = np.asarray(slots).tolist()
                slots = dense + slots
        elif not isinstance(slots, list):
            slots = np.asarray(slots).tolist()
        slot_subs = self._slot_subs
        nsubs = len(slot_subs)
        seen = set() if dedup else None
        for slot in slots:
            # -1 pads (compact rows) and slots past the local table
            # (another node's lanes) skip here — plain int compares,
            # no per-row numpy filter pass
            if slot < 0 or slot >= nsubs:
                continue
            if seen is not None:
                if slot in seen:
                    continue
                seen.add(slot)
            sub = slot_subs[slot]
            if sub is None:
                continue
            if sub.opts.no_local and sub.client_id == msg.from_client:
                continue
            # staleness net: the kernel ran against a snapshot, and slots /
            # filter ids freed during an in-flight batch can be reused by
            # unrelated subscriptions — verify the sub's filter really
            # matches before delivering (misdelivery is worse than a
            # topic-match check per delivery). Exact filters (the serving
            # common case) short-circuit on string equality; the full
            # matcher is memoized per batch (pure fn of (topic, filter))
            f = sub.filter
            if topic != f:
                ok = match_memo.get((topic, f))
                if ok is None:
                    ok = T.match(topic, f)
                    match_memo[(topic, f)] = ok
                if not ok:
                    continue
            n += self._deliver_one(sub, msg)
        if picks is not None:
            # device-resolved $share picks: host does delivery + failover
            gids, idxs = picks
            for gid, idx in zip(gids, idxs):
                if gid < 0:
                    continue
                info = self.grouptab.info(int(gid))
                if info is None:
                    continue  # group dropped while the batch was in flight
                real, gname = info
                # staleness net, same as slots: re-verify the filter
                ok = match_memo.get((topic, real))
                if ok is None:
                    ok = T.match(topic, real)
                    match_memo[(topic, real)] = ok
                if not ok:
                    continue
                n += self.shared.dispatch_picked(real, gname, int(idx), msg)
                if touched_gids is not None:
                    touched_gids.add(int(gid))
        else:
            for fid in fids:
                fid = int(fid)
                ent = fid_memo.get(fid)
                if ent is None:
                    name = self.router.filter_name(fid)
                    ent = (
                        name,
                        name is not None and self.shared.has_groups(name),
                    )
                    fid_memo[fid] = ent
                name, has_g = ent
                if not has_g:
                    continue
                ok = match_memo.get((topic, name))
                if ok is None:
                    ok = T.match(topic, name)
                    match_memo[(topic, name)] = ok
                if ok:
                    n += self.shared.dispatch_groups(name, msg)
        if stats is not None:
            stats.append(n)  # caller batches the metric upkeep
            return n
        self.metrics.observe("dispatch.fanout", n)
        if n:
            self.metrics.inc("messages.delivered", n)
        return n

    def _sync_group_counters(self, gids) -> None:
        """Push advanced round-robin bases / sticky pins back to the
        device mirror — called once per BATCH with the touched gid set,
        so churn is one bounded write per group per batch."""
        for gid in gids:
            info = self.grouptab.info(gid)
            if info is None:
                continue
            g = self.shared.group(*info)
            if g is None:
                continue
            self.grouptab.set_rr(gid, g.rr_index)
            if self.shared.strategy == "sticky" and g.sticky_sid is not None:
                self.grouptab.repin(gid, g.members.keys(), g.sticky_sid)

    def dispatch(self, filters: List[str], msg: Message) -> int:
        """Deliver to local subscribers of pre-matched filters.

        This is the receiving half of a cross-node forward: the publisher
        node already ran the route match, the owner node fans out to its
        local subscriber tables (emqx_broker:dispatch, emqx_broker.erl:
        505-530 via the forward path :278-293).
        """
        rec = self.spans
        t_ns = (
            rec.now_ns()
            if rec is not None and TRACE_HEADER in msg.headers
            else 0
        )
        n = self._route_dispatch(msg, filters)
        if t_ns:
            # the context rode the forward in the message headers: this
            # deliver span keeps the ORIGIN node's trace_id
            rec.deliver(msg, n, start_ns=t_ns, remote=True)
        return n

    def has_local_subs(self, route_key: str) -> bool:
        """Any local subscriber (plain or shared-group) on this filter?"""
        return bool(self._subs.get(route_key)) or self.shared.has_groups(
            route_key
        )

    def _route_dispatch(self, msg: Message, filters: List[str]) -> int:
        self.metrics.inc("messages.received")
        if msg.headers.get("_batch_rules") and self.rule_hook is not None:
            # a deferred-rule message settling OUTSIDE the batch paths
            # (sync publish, device-flagged fallback rows whose batch
            # carried no masks): fire through the host ladder
            self.rule_hook.fire_settled([msg])
        n = 0
        for f in filters:
            # one matched filter may carry plain subscribers AND shared groups
            entry = self._subs.get(f)
            if entry:
                for sub in list(entry.values()):
                    if sub.opts.no_local and sub.client_id == msg.from_client:
                        continue
                    if sub.semantic:
                        # embedding-filtered: delivery needs similarity
                        # too — resolved by the host twin below
                        continue
                    n += self._deliver_one(sub, msg)
            n += self.shared.dispatch_groups(f, msg)
        sem = self.semantic
        if sem is not None and len(sem.table):
            # the authoritative host twin (CPU fallback / per-message
            # path): topic-scope AND similarity, global top-k
            for slot in sem.host_route([msg])[0]:
                sub = (
                    self._slot_subs[slot]
                    if 0 <= slot < len(self._slot_subs)
                    else None
                )
                if sub is None:
                    continue
                if sub.opts.no_local and sub.client_id == msg.from_client:
                    continue
                n += self._deliver_one(sub, msg)
        self.metrics.observe("dispatch.fanout", n)
        if n:
            self.metrics.inc("messages.delivered", n)
        return n

    def _deliver_one(self, sub: Subscriber, msg: Message) -> int:
        """One raising deliverer must not poison the rest of the fan-out
        (or, on the batch path, every other message in the batch)."""
        try:
            sub.deliver(msg, sub.opts)
            return 1
        except Exception:
            self.metrics.inc("delivery.errors")
            return 0

    def drop_session_subs(self, sid: str, filters: Sequence[str]) -> None:
        """Bulk cleanup when a session dies (emqx_broker_helper pmon parity)."""
        for f in list(filters):
            self.unsubscribe(sid, f)
