"""The pub/sub kernel: subscribe/unsubscribe/publish/dispatch.

Parity with the reference kernel (apps/emqx/src/emqx_broker.erl):
- subscribe/unsubscribe maintain the subscriber registry + route table
  (emqx_broker.erl:127-160 ETS inserts + :441-454 route add)
- publish runs the 'message.publish' fold, matches routes, and dispatches
  to local subscribers (:204-215 publish, :505-530 do_dispatch)
- publish_batch is the TPU-era addition: many topics matched in one device
  kernel, then fanned out (the reference has no batch path — its hot loop
  is per-message, which is exactly what this design replaces)

Dispatch hands (session, opts, msg) triples to each subscriber's channel via
the session's registered deliver callback. Shared-subscription groups
($share/g/t) are delegated to SharedSub.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

from emqx_tpu.broker.hooks import Hooks, default_hooks
from emqx_tpu.broker.message import Message
from emqx_tpu.broker.metrics import Metrics
from emqx_tpu.broker.router import Router
from emqx_tpu.broker.shared_sub import SharedSub
from emqx_tpu.mqtt import packet as pkt
from emqx_tpu.ops import topics as T

# deliverer: called with (msg, subopts); returns True if accepted
Deliverer = Callable[[Message, pkt.SubOpts], None]


class Subscriber:
    __slots__ = ("sid", "deliver", "opts", "client_id")

    def __init__(self, sid: str, client_id: str, deliver: Deliverer, opts: pkt.SubOpts):
        self.sid = sid
        self.client_id = client_id
        self.deliver = deliver
        self.opts = opts


class Broker:
    def __init__(
        self,
        router: Optional[Router] = None,
        hooks: Optional[Hooks] = None,
        metrics: Optional[Metrics] = None,
    ):
        self.router = router or Router()
        self.hooks = hooks or default_hooks
        self.metrics = metrics or Metrics()
        # filter -> {sid -> Subscriber}
        self._subs: Dict[str, Dict[str, Subscriber]] = {}
        self.shared = SharedSub()

    # -- subscribe side ---------------------------------------------------
    def subscribe(
        self,
        sid: str,
        client_id: str,
        filter_: str,
        opts: pkt.SubOpts,
        deliver: Deliverer,
    ) -> None:
        group, real = T.parse_share(filter_)
        sub = Subscriber(sid, client_id, deliver, opts)
        if group is not None:
            self.shared.subscribe(group, real, sub)
            route_key = self.shared.route_filter(group, real)
        else:
            entry = self._subs.setdefault(real, {})
            first = not entry
            entry[sid] = sub
            route_key = real if first else None
        if route_key is not None:
            self.router.add_route(route_key)
        self.metrics.gauge_set("subscriptions.count", self.subscription_count())

    def unsubscribe(self, sid: str, filter_: str) -> bool:
        group, real = T.parse_share(filter_)
        if group is not None:
            removed, empty = self.shared.unsubscribe(group, real, sid)
            if empty:
                self.router.delete_route(self.shared.route_filter(group, real))
            return removed
        entry = self._subs.get(real)
        if not entry or sid not in entry:
            return False
        del entry[sid]
        if not entry:
            del self._subs[real]
            self.router.delete_route(real)
        self.metrics.gauge_set("subscriptions.count", self.subscription_count())
        return True

    def subscription_count(self) -> int:
        return sum(len(v) for v in self._subs.values()) + self.shared.count()

    def subscriptions(self) -> List[Tuple[str, str, pkt.SubOpts]]:
        out = []
        for f, entry in self._subs.items():
            for sub in entry.values():
                out.append((sub.client_id, f, sub.opts))
        out.extend(self.shared.subscriptions())
        return out

    # -- publish side -----------------------------------------------------
    def publish(self, msg: Message) -> int:
        """Route + dispatch one message; returns delivery count."""
        msg = self.hooks.run_fold("message.publish", (), msg)
        return self._publish_folded(msg)

    async def apublish(self, msg: Message) -> int:
        """Async `publish` for the connection path: awaits async hooks
        (exhook sidecars) so a slow extension suspends only the publishing
        client's task, not the event loop."""
        msg = await self.hooks.arun_fold("message.publish", (), msg)
        return self._publish_folded(msg)

    def _publish_folded(self, msg: Optional[Message]) -> int:
        """Shared tail of publish/apublish after the message.publish fold."""
        if msg is None or msg.headers.get("allow_publish") is False:
            self.metrics.inc("messages.dropped")
            return 0
        n = self._route_dispatch(msg, self.router.match(msg.topic))
        if n == 0:
            self.hooks.run("message.dropped", msg, "no_subscribers")
            self.metrics.inc("messages.dropped.no_subscribers")
        return n

    def publish_batch(self, msgs: Sequence[Message]) -> int:
        """Batch publish: one TPU kernel for all topics, then fan out."""
        msgs2: List[Message] = []
        for m in msgs:
            m = self.hooks.run_fold("message.publish", (), m)
            if m is not None and m.headers.get("allow_publish") is not False:
                msgs2.append(m)
        matches = self.router.match_batch([m.topic for m in msgs2])
        total = 0
        for m, filters in zip(msgs2, matches):
            n = self._route_dispatch(m, filters)
            if n == 0:
                self.hooks.run("message.dropped", m, "no_subscribers")
            total += n
        return total

    def dispatch(self, filters: List[str], msg: Message) -> int:
        """Deliver to local subscribers of pre-matched filters.

        This is the receiving half of a cross-node forward: the publisher
        node already ran the route match, the owner node fans out to its
        local subscriber tables (emqx_broker:dispatch, emqx_broker.erl:
        505-530 via the forward path :278-293).
        """
        return self._route_dispatch(msg, filters)

    def has_local_subs(self, route_key: str) -> bool:
        """Any local subscriber (plain or shared-group) on this filter?"""
        return bool(self._subs.get(route_key)) or self.shared.has_groups(
            route_key
        )

    def _route_dispatch(self, msg: Message, filters: List[str]) -> int:
        self.metrics.inc("messages.received")
        n = 0
        for f in filters:
            # one matched filter may carry plain subscribers AND shared groups
            entry = self._subs.get(f)
            if entry:
                for sub in list(entry.values()):
                    if sub.opts.no_local and sub.client_id == msg.from_client:
                        continue
                    sub.deliver(msg, sub.opts)
                    n += 1
            n += self.shared.dispatch_groups(f, msg)
        if n:
            self.metrics.inc("messages.delivered", n)
        return n

    def drop_session_subs(self, sid: str, filters: Sequence[str]) -> None:
        """Bulk cleanup when a session dies (emqx_broker_helper pmon parity)."""
        for f in list(filters):
            self.unsubscribe(sid, f)
