"""Hierarchical token-bucket rate limiting.

Parity with the reference's limiter sub-app (apps/emqx/src/emqx_limiter/,
SURVEY.md §2.1): a per-node limiter server holds one root bucket per limit
type (bytes_in, message_in, connection, message_routing); every connection
gets a container of per-type clients, each with an optional private bucket
chained to the shared root.

Two consumption modes, matching the two callers in the reference:
- `consume(n)` — **charge-and-pause**: the tokens are always charged (the
  bucket may go into debt) and the returned float is how long the caller
  must sleep before proceeding, so sustained throughput converges to the
  configured rate for any n, including reads larger than the bucket
  capacity (emqx_connection's pause/retry loop, emqx_connection.erl:
  103-120,474-483).
- `try_acquire(n)` — **refuse-don't-queue**: consume only if n tokens are
  available now; used for connection admission where the reference refuses
  the socket instead of queueing it.

Infinity (rate<=0) means unlimited, matching the reference's `infinity`
default for every type.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class BucketConfig:
    rate: float = 0.0  # tokens/second; <=0 = unlimited
    burst: float = 0.0  # bucket capacity; <=0 = rate (1s worth)

    @property
    def unlimited(self) -> bool:
        return self.rate <= 0

    @property
    def capacity(self) -> float:
        return self.burst if self.burst > 0 else self.rate


class TokenBucket:
    __slots__ = ("rate", "capacity", "tokens", "last")

    def __init__(self, rate: float, capacity: float):
        self.rate = rate
        self.capacity = capacity
        self.tokens = capacity
        self.last: Optional[float] = None  # baseline = first observed clock

    def _refill(self, now: float) -> None:
        if self.last is None:
            self.last = now
        if now > self.last:
            self.tokens = min(
                self.capacity, self.tokens + (now - self.last) * self.rate
            )
            self.last = now

    def consume(self, n: float, now: Optional[float] = None) -> float:
        """Charge n tokens unconditionally (debt allowed); returns the pause
        in seconds the caller should sleep so throughput matches `rate`."""
        now = now if now is not None else time.monotonic()
        self._refill(now)
        self.tokens -= n
        if self.tokens >= 0:
            return 0.0
        if self.rate <= 0:
            return float("inf")
        return -self.tokens / self.rate

    def try_acquire(self, n: float, now: Optional[float] = None) -> bool:
        """Consume n only if available now; no debt (admission control)."""
        now = now if now is not None else time.monotonic()
        self._refill(now)
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False


class LimiterClient:
    """Per-connection view of one limit type: private bucket + shared root."""

    __slots__ = ("_local", "_root")

    MAX_PAUSE = 60.0

    def __init__(
        self, local: Optional[TokenBucket], root: Optional[TokenBucket]
    ):
        self._local = local
        self._root = root

    def consume(self, n: float = 1.0) -> float:
        """Charge both buckets; returns the pause (seconds) to sleep."""
        now = time.monotonic()
        wait = 0.0
        if self._local is not None:
            wait = self._local.consume(n, now)
        if self._root is not None:
            wait = max(wait, self._root.consume(n, now))
        return min(wait, self.MAX_PAUSE)

    def try_acquire(self, n: float = 1.0) -> bool:
        """Both buckets must have tokens now; no debt on refusal."""
        now = time.monotonic()
        if self._local is not None and not self._local.try_acquire(n, now):
            return False
        if self._root is not None and not self._root.try_acquire(n, now):
            if self._local is not None:
                self._local.tokens = min(
                    self._local.capacity, self._local.tokens + n
                )
            return False
        return True

    @property
    def unlimited(self) -> bool:
        return self._local is None and self._root is None


_UNLIMITED = LimiterClient(None, None)

TYPES = ("bytes_in", "message_in", "connection", "message_routing")


class LimiterServer:
    """Node-level roots + per-client bucket factory (emqx_limiter_server)."""

    def __init__(self, config: Optional[Dict[str, Dict]] = None):
        """config: {type: {"rate": r, "burst": b,
                           "client": {"rate": r, "burst": b}}}"""
        self._roots: Dict[str, TokenBucket] = {}
        self._client_cfg: Dict[str, BucketConfig] = {}
        self.reconfigure(config)

    def reconfigure(self, config: Optional[Dict[str, Dict]]) -> None:
        """Rebuild buckets from a new config (runtime update path,
        emqx_config_handler -> limiter). Existing LimiterClients keep
        their old shared roots until reconnect; new connections pick up
        the new rates immediately."""
        roots: Dict[str, TokenBucket] = {}
        client_cfgs: Dict[str, BucketConfig] = {}
        for type_, spec in (config or {}).items():
            if type_ not in TYPES:
                raise ValueError(f"unknown limiter type {type_!r}")
            root = BucketConfig(
                rate=float(spec.get("rate", 0) or 0),
                burst=float(spec.get("burst", 0) or 0),
            )
            if not root.unlimited:
                roots[type_] = TokenBucket(root.rate, root.capacity)
            client = spec.get("client") or {}
            ccfg = BucketConfig(
                rate=float(client.get("rate", 0) or 0),
                burst=float(client.get("burst", 0) or 0),
            )
            if not ccfg.unlimited:
                client_cfgs[type_] = ccfg
        self._roots = roots
        self._client_cfg = client_cfgs

    def limited(self, type_: str) -> bool:
        return type_ in self._roots or type_ in self._client_cfg

    def connect(self, type_: str) -> LimiterClient:
        root = self._roots.get(type_)
        ccfg = self._client_cfg.get(type_)
        if root is None and ccfg is None:
            return _UNLIMITED
        local = (
            TokenBucket(ccfg.rate, ccfg.capacity) if ccfg is not None else None
        )
        return LimiterClient(local, root)

    def container(self, *types: str) -> Optional["LimiterContainer"]:
        """None when every requested type is unlimited, so hot paths can
        skip limiter work entirely with one is-None check."""
        types = types or TYPES
        if not any(self.limited(t) for t in types):
            return None
        return LimiterContainer({t: self.connect(t) for t in types})


@dataclass
class LimiterContainer:
    """One connection's set of limiter clients (emqx_limiter_container)."""

    clients: Dict[str, LimiterClient] = field(default_factory=dict)

    def consume(self, type_: str, n: float = 1.0) -> float:
        c = self.clients.get(type_)
        return c.consume(n) if c is not None else 0.0
