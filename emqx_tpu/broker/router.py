"""Route table: exact-topic index + wildcard trie + TPU batch engine.

Parity with the reference's split storage (apps/emqx/src/emqx_router.erl:
111-125: plain topics go straight into the route table via dirty insert,
wildcard topics also enter the trie inside a transaction; match =
trie match + direct lookup, :128-141):

- exact (non-wildcard) filters: refcounted dict, O(1) lookup per topic;
- wildcard filters: the authoritative CPU trie (`TopicTrie`);
- BOTH feed the `RouteIndex` (shape-hash fast path + residual NFA,
  ops/route_index.py), so the TPU batch path resolves every filter kind in
  one kernel and the CPU path is only a correctness fallback/small-batch
  shortcut.

`match_batch` picks the TPU path when the batch is big enough to amortize a
dispatch (min_tpu_batch), mirroring how the reference splits work between
the caller process and the router worker pool (emqx_router.erl:188-189).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from emqx_tpu.broker.trie import TopicTrie
from emqx_tpu.ops import topics as T
from emqx_tpu.ops.matcher import MatcherConfig
from emqx_tpu.ops.route_index import RouteIndex


class Router:
    def __init__(
        self,
        matcher_config: Optional[MatcherConfig] = None,
        min_tpu_batch: int = 64,
        enable_tpu: bool = True,
    ):
        self._exact: Dict[str, int] = {}
        self._trie = TopicTrie()
        self._index = RouteIndex()
        self._matcher = None  # lazy match-only DeviceRouter
        self._matcher_config = matcher_config or MatcherConfig()
        self.min_tpu_batch = min_tpu_batch
        self.enable_tpu = enable_tpu
        # ('dp','tp') jax Mesh, set by the app alongside broker.mesh:
        # the lazy match-only engine then uploads its table mirrors
        # pre-sharded (replicated NamedSharding) like the serving engine
        self.mesh = None

    def __getstate__(self):
        # segment-state snapshots (ops/segments.SegmentStateSnapshot)
        # pickle the router; the lazy DeviceRouter holds device buffers
        # and is rebuilt on first use after restore. The mesh holds
        # live device objects (unpicklable by design) — the restoring
        # process re-attaches its OWN mesh (app boot wiring).
        d = self.__dict__.copy()
        d["_matcher"] = None
        d["mesh"] = None
        return d

    def __len__(self) -> int:
        return len(self._exact) + len(self._trie)

    def topics(self) -> List[str]:
        return list(self._exact) + list(self._trie.filters())

    def has_route(self, filter_: str) -> bool:
        return filter_ in self._exact or self._trie.has(filter_)

    def add_route(self, filter_: str) -> int:
        """Refcounted insert (one ref per subscriber entry). Returns the
        filter id so subscribe-storm callers skip a registry re-probe."""
        fid = self._index.add(filter_)
        if T.wildcard(filter_):
            self._trie.insert(filter_)
        else:
            self._exact[filter_] = self._exact.get(filter_, 0) + 1
        return fid

    def delete_route(self, filter_: str) -> None:
        self._index.remove(filter_)
        if T.wildcard(filter_):
            self._trie.delete(filter_)
        else:
            n = self._exact.get(filter_, 0) - 1
            if n > 0:
                self._exact[filter_] = n
            else:
                self._exact.pop(filter_, None)

    # -- matching ---------------------------------------------------------
    def match(self, topic: str) -> List[str]:
        """CPU single-topic match: direct lookup + trie walk."""
        out = []
        if topic in self._exact:
            out.append(topic)
        out.extend(self._trie.match(topic))
        return out

    def match_batch(self, topics: Sequence[str]) -> List[List[str]]:
        if not self.enable_tpu or len(topics) < self.min_tpu_batch:
            return [self.match(t) for t in topics]
        return self.matcher.match_batch(topics, fallback=self.match)

    def filter_id(self, filter_: str) -> Optional[int]:
        return self._index.filter_id(filter_)

    def filter_name(self, fid: int) -> Optional[str]:
        return self._index.filter_name(fid)

    @property
    def index(self) -> RouteIndex:
        return self._index

    @property
    def matcher(self):
        """Match-only device engine (its own table mirror; the broker's
        fan-out DeviceRouter keeps a separate one)."""
        if self._matcher is None:
            from emqx_tpu.models.router_model import DeviceRouter

            self._matcher = DeviceRouter(
                self._index, None, self._matcher_config, mesh=self.mesh
            )
        return self._matcher

    @property
    def matcher_config(self) -> MatcherConfig:
        return self._matcher_config
