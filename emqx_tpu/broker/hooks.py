"""Hook registry: priority-ordered callback chains per hookpoint.

Parity with the reference's extension spine (apps/emqx/src/emqx_hooks.erl:
30-41 API, 163-196 run/run_fold with 'stop' short-circuit). Every extension
in the reference attaches here (authn/authz, rule engine, retainer, exhook —
SURVEY.md §2 L4); this framework keeps the same contract so extensions stay
decoupled from the broker kernel.

Hookpoint names mirror the canonical enumeration in the reference's
exhook.proto (apps/emqx_exhook/priv/protos/exhook.proto:27-69):
client.connect/connack/connected/disconnected/authenticate/authorize/
subscribe/unsubscribe, session.created/subscribed/unsubscribed/resumed/
discarded/takenover/terminated, message.publish/delivered/acked/dropped,
delivery.dropped/completed.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, List, Optional, Tuple


class StopAndReturn(Exception):
    """Raised by a callback to short-circuit a fold with a final value."""

    def __init__(self, value):
        self.value = value


STOP = object()  # sentinel return: stop the chain (keep current acc)


class Hooks:
    def __init__(self) -> None:
        self._table: Dict[str, List[Tuple[int, str, Callable]]] = {}

    def add(
        self,
        name: str,
        callback: Callable,
        priority: int = 0,
        tag: Optional[str] = None,
    ) -> None:
        """Register; higher priority runs first (emqx_hooks.erl ordering)."""
        chain = self._table.setdefault(name, [])
        tag = tag or getattr(callback, "__qualname__", repr(callback))
        chain.append((priority, tag, callback))
        chain.sort(key=lambda e: -e[0])

    def delete(self, name: str, callback_or_tag) -> None:
        chain = self._table.get(name, [])
        self._table[name] = [
            e
            for e in chain
            if e[2] is not callback_or_tag and e[1] != callback_or_tag
        ]

    def run(self, name: str, *args) -> None:
        """Run all callbacks; a STOP return short-circuits."""
        for _, _, cb in self._table.get(name, ()):  # snapshot-free; small N
            if cb(*args) is STOP:
                return

    def run_fold(self, name: str, args: tuple, acc: Any) -> Any:
        """Fold acc through the chain.

        Callback returns: None (keep acc) | ('ok', new_acc) | STOP |
        ('stop', final_acc); or raises StopAndReturn(final).
        """
        for _, _, cb in self._table.get(name, ()):
            try:
                r = cb(*args, acc)
            except StopAndReturn as s:
                return s.value
            if r is None or r is True:
                continue
            if r is STOP:
                return acc
            if isinstance(r, tuple) and len(r) == 2:
                kind, val = r
                if kind == "ok":
                    acc = val
                    continue
                if kind == "stop":
                    return val
            acc = r  # plain new acc
        return acc

    def callbacks(self, name: str):
        return list(self._table.get(name, ()))


# process-global default registry (the reference's hooks are node-global)
default_hooks = Hooks()
