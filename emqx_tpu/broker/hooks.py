"""Hook registry: priority-ordered callback chains per hookpoint.

Parity with the reference's extension spine (apps/emqx/src/emqx_hooks.erl:
30-41 API, 163-196 run/run_fold with 'stop' short-circuit). Every extension
in the reference attaches here (authn/authz, rule engine, retainer, exhook —
SURVEY.md §2 L4); this framework keeps the same contract so extensions stay
decoupled from the broker kernel.

Hookpoint names mirror the canonical enumeration in the reference's
exhook.proto (apps/emqx_exhook/priv/protos/exhook.proto:27-69):
client.connect/connack/connected/disconnected/authenticate/authorize/
subscribe/unsubscribe, session.created/subscribed/unsubscribed/resumed/
discarded/takenover/terminated, message.publish/delivered/acked/dropped,
delivery.dropped/completed.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, List, Optional, Tuple


class StopAndReturn(Exception):
    """Raised by a callback to short-circuit a fold with a final value."""

    def __init__(self, value):
        self.value = value


STOP = object()  # sentinel return: stop the chain (keep current acc)


class Hooks:
    def __init__(self) -> None:
        # chain entries: (priority, tag, callback, is_coroutine_fn) —
        # coroutine-ness is classified ONCE at registration; the fold
        # paths run per message and inspect.iscoroutinefunction there
        # measured as the single largest hook-framework cost
        self._table: Dict[str, List[Tuple[int, str, Callable, bool]]] = {}

    def add(
        self,
        name: str,
        callback: Callable,
        priority: int = 0,
        tag: Optional[str] = None,
    ) -> None:
        """Register; higher priority runs first (emqx_hooks.erl ordering)."""
        chain = self._table.setdefault(name, [])
        tag = tag or getattr(callback, "__qualname__", repr(callback))
        chain.append(
            (priority, tag, callback, inspect.iscoroutinefunction(callback))
        )
        chain.sort(key=lambda e: -e[0])

    def delete(self, name: str, callback_or_tag) -> None:
        chain = self._table.get(name, [])
        self._table[name] = [
            e
            for e in chain
            if e[2] is not callback_or_tag and e[1] != callback_or_tag
        ]

    def run(self, name: str, *args) -> None:
        """Run all callbacks; a STOP return short-circuits.

        Coroutine-function callbacks are skipped on this sync path (they
        only fire on `arun`); the async channel path uses arun/arun_fold so
        client-originated traffic always reaches async extensions (exhook).
        """
        for _, _, cb, is_coro in self._table.get(name, ()):
            if is_coro:
                continue
            if cb(*args) is STOP:
                return

    def run_fold(self, name: str, args: tuple, acc: Any) -> Any:
        """Fold acc through the chain.

        Callback returns: None (keep acc) | ('ok', new_acc) | STOP |
        ('stop', final_acc); or raises StopAndReturn(final).
        Coroutine-function callbacks are skipped (see `run`).
        """
        for _, _, cb, is_coro in self._table.get(name, ()):
            if is_coro:
                continue
            try:
                r = cb(*args, acc)
            except StopAndReturn as s:
                return s.value
            acc2, stop = self._fold_step(r, acc)
            if stop:
                return acc2
            acc = acc2
        return acc

    @staticmethod
    def _fold_step(r, acc) -> Tuple[Any, bool]:
        """-> (new_acc, stop?)"""
        if r is None or r is True:
            return acc, False
        if r is STOP:
            return acc, True
        if isinstance(r, tuple) and len(r) == 2:
            kind, val = r
            if kind == "ok":
                return val, False
            if kind == "stop":
                return val, True
        return r, False  # plain new acc

    async def arun(self, name: str, *args) -> None:
        """Async `run`: awaits coroutine callbacks, runs sync ones inline.

        This is the channel-path variant — a slow async extension (e.g. an
        exhook gRPC sidecar) suspends only the calling connection's task,
        never the event loop (ADVICE r1: emqx_exhook blocking finding).
        """
        for _, _, cb, _is_coro in self._table.get(name, ()):
            r = cb(*args)
            if inspect.isawaitable(r):
                r = await r
            if r is STOP:
                return

    async def arun_fold(self, name: str, args: tuple, acc: Any) -> Any:
        """Async `run_fold`: awaits coroutine callbacks along the chain.
        (isawaitable stays per-result: a SYNC callback may still return
        an awaitable it built — only the registration-time coroutine
        check is cached.)"""
        for _, _, cb, _is_coro in self._table.get(name, ()):
            try:
                r = cb(*args, acc)
                if inspect.isawaitable(r):
                    r = await r
            except StopAndReturn as s:
                return s.value
            acc2, stop = self._fold_step(r, acc)
            if stop:
                return acc2
            acc = acc2
        return acc

    def callbacks(self, name: str):
        return list(self._table.get(name, ()))


# process-global default registry (the reference's hooks are node-global)
default_hooks = Hooks()
