"""Ingest-side publish batch aggregation (SLO-adaptive batch window).

SURVEY.md §7 hard part (c): the device route path wants big batches, but a
publishing client wants low latency. This aggregator sits between the
channel's publish and the router: concurrent publishes from all connections
collect into priority lanes, flushed when either `max_batch` messages are
pending or the window has elapsed since the flusher woke — so a lone
publisher pays at most one window of added latency while a firehose fills
batches immediately and never sleeps.

The window is no longer a fixed policy: with an `SloController` attached
(broker/slo.py), it adapts each flush cycle to hold a configured
enqueue->settle p99 target — decaying toward zero when idle (immediate
partial launches), deepening under storm, and walking the graded
backpressure ladder (widen -> defer low lanes -> shed) instead of the old
binary `IngestShed` cliff.

Priority lanes: `control` (QoS2 control flow, $SYS) > `normal` (QoS1) >
`low` (QoS0 firehose when `qos0_low`, explicitly tagged messages). The
flusher assembles batches in lane order with an anti-starvation reserve,
so a retained-storm or QoS0 flood can never queue a PUBREL or a $SYS
heartbeat behind itself (docs/robustness.md "Priority lanes").

The reference has no analog — its hot loop is per-message per-process
(emqx_broker.erl:204-215); this is the TPU-era replacement for that regime,
turning N concurrent publishes into one route_step kernel launch
(emqx_tpu.models.router_model.DeviceRouter).

Backpressure: `submit` awaits the flush result, so a publisher's PUBACK
reflects actual dispatch; the pending lanes are bounded by the shed ladder
(SLO mode) or the legacy overload gate.

Flight recorder: every latency/throughput tradeoff this loop makes is
recorded into the broker's metrics (docs/observability.md) — batch size and
occupancy, window hold time, pipeline depth, per-message AND per-lane
enqueue->settle latency, lane depths, and launch/dispatch failures — plus
`ingest.launch`/`ingest.settle` tracepoints keyed by batch seq for causal
assertions in tests.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import List, Optional, Tuple

from emqx_tpu.broker.degrade import OPEN, IngestShed
from emqx_tpu.broker.message import Message
from emqx_tpu.broker.metrics import Metrics
from emqx_tpu.broker.slo import (
    LANE_CONTROL,
    LANE_LOW,
    LANE_NAMES,
    LANE_NORMAL,
    RUNG_NAMES,
)
from emqx_tpu.observe import faults as _faults
from emqx_tpu.observe.spans import TRACE_HEADER
from emqx_tpu.utils.tracepoints import tp

log = logging.getLogger("emqx_tpu.ingest")

LANE_DEPTH_SERIES = tuple(f"ingest.lane.depth.{n}" for n in LANE_NAMES)
LANE_SETTLE_SERIES = tuple(
    f"ingest.lane.settle.seconds.{n}" for n in LANE_NAMES
)


class BatchIngest:
    def __init__(
        self,
        broker,
        max_batch: int = 4096,
        window_us: int = 1000,
        pipeline: int = 2,
        olp=None,
        slo=None,
        qos0_low: bool = False,
    ):
        self.broker = broker
        self.max_batch = max_batch
        self.window_s = window_us / 1e6
        # overload-protection signal (broker/olp.py): with the broker's
        # DegradeController attached (and no SLO controller), enqueues
        # shed once the pending backlog passes the shed bound while
        # olp.is_overloaded() holds or the device breaker is open —
        # backpressure instead of unbounded queue growth behind a broken
        # fast path. With an SloController the graded ladder owns
        # admission instead (shed is the LAST rung).
        self.olp = olp
        # SLO-adaptive batching (broker/slo.py): adapts window_s each
        # flush cycle + owns the defer/shed ladder. None = legacy fixed
        # window (unit tests, knob off).
        self.slo = slo
        # lane policy: route QoS0 publishes to the low-priority lane
        # (the firehose a $SYS heartbeat must never queue behind)
        self.qos0_low = qos0_low
        # device dispatches in flight at once: batch N+1's table upload +
        # kernel launch overlaps batch N's readback round-trip (the
        # dominant per-batch wall when the chip sits behind a network
        # tunnel; on a local chip it overlaps host fan-out with device
        # compute). Settlement stays strictly FIFO so per-publisher
        # delivery order holds across batches.
        self.pipeline = max(1, pipeline)
        self.metrics: Metrics = getattr(broker, "metrics", None) or Metrics()
        # per-lane pending lists of (msg, puback future, enqueue
        # perf_counter timestamp, lane). `_pending` stays the NORMAL
        # lane's list (the historical name — shed/backlog tests and the
        # stop() drain reach it directly).
        self._lane_hi: List[Tuple] = []
        self._pending: List[Tuple] = []
        self._lane_lo: List[Tuple] = []
        self._inflight: deque = deque()  # (seq, batch, pending, batch_span)
        self._event = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._seq = 0
        # anti-starvation bound for the low lane under sustained
        # control/normal pressure (SloController overrides from config)
        self.starvation_s = slo.starvation_s if slo is not None else 0.05
        # perf_counter stamp of the moment the LAST in-flight dispatch's
        # device work completed (None = device busy or never launched);
        # the gap until the next launch is the ingest.device.idle series
        self._device_done_t: Optional[float] = None
        self.running = False

    def start(self) -> None:
        if self._task is None:
            self.running = True
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self.running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        # drain launched-but-unsettled batches first (FIFO), then
        # anything still pending (defer gates ignored: shutdown delivers
        # everything), so no publisher hangs on shutdown
        while self._inflight:
            seq, batch, pd, bsp = self._inflight.popleft()
            await self._finish(seq, batch, pd.complete(), bsp)
        while self._backlog():
            batch = self._take_batch(time.perf_counter(), force=True)
            await self._settle(batch)

    # -- lanes --------------------------------------------------------------
    def _backlog(self) -> int:
        return len(self._lane_hi) + len(self._pending) + len(self._lane_lo)

    def lane_of(self, msg: Message) -> int:
        """Priority-lane classification (docs/robustness.md): QoS2
        control flow and $SYS ride the control lane (they must never
        queue behind a firehose); QoS0 rides low when the lane policy is
        armed; explicit `ingest_lane` headers win."""
        ln = msg.headers.get("ingest_lane")
        if ln == "control":
            return LANE_CONTROL
        if ln == "low":
            return LANE_LOW
        if msg.qos == 2 or msg.is_sys():
            return LANE_CONTROL
        if msg.qos == 0 and self.qos0_low:
            return LANE_LOW
        return LANE_NORMAL

    def _lane_list(self, lane: int) -> List[Tuple]:
        if lane == LANE_CONTROL:
            return self._lane_hi
        if lane == LANE_LOW:
            return self._lane_lo
        return self._pending

    def enqueue(self, msg: Message, lane: Optional[int] = None) -> asyncio.Future:
        """Enqueue one folded message; the future resolves with its
        delivery count when the batch flushes.

        Admission (docs/robustness.md): with an SloController attached,
        the graded ladder decides — control never sheds, low sheds at
        the queue bound on the `shed` rung, normal at twice the bound,
        and `shed_hard_mult` x bound is the absolute valve. Without a
        controller the legacy binary gate holds: while the broker is
        overloaded (olp) or the device breaker is open, a backlog past
        the shed bound refuses new enqueues with `IngestShed` on the
        returned future — the publisher's PUBACK fails (QoS>=1 clients
        retry) instead of the pending list growing without bound."""
        act = _faults.hit("ingest.enqueue")  # raise -> publisher's task
        fut = asyncio.get_running_loop().create_future()
        if lane is None:
            lane = self.lane_of(msg)
        shed = act == "drop"
        deg = getattr(self.broker, "degrade", None)
        if not shed and deg is not None:
            bound = deg.shed_queue_batches * self.max_batch
            if self.slo is not None:
                if self.slo.shed(lane, self._backlog(), bound):
                    shed = True
                    self.metrics.inc("slo.shed")
            elif (
                len(self._pending) >= bound
                and (
                    (self.olp is not None and self.olp.is_overloaded())
                    or deg.device.state == OPEN
                )
            ):
                shed = True
        if shed:
            self.metrics.inc("ingest.shed")
            fut.set_exception(
                IngestShed("ingest backlog shed (overload/degraded)")
            )
            return fut
        self._lane_list(lane).append((msg, fut, time.perf_counter(), lane))
        self._event.set()
        return fut

    async def submit(self, msg: Message) -> int:
        return await self.enqueue(msg)

    def _take_batch(self, now: float, force: bool = False) -> List[Tuple]:
        """Assemble up to max_batch in lane-priority order. The low lane
        joins unless the SLO ladder defers it (never past its defer age
        bound); a starvation reserve guarantees the low lane slots once
        its head has waited `starvation_s` behind full priority lanes.
        `force` (shutdown drain) ignores the defer gate."""
        cap = self.max_batch
        batch: List[Tuple] = []
        hi, no, lo = self._lane_hi, self._pending, self._lane_lo
        if hi:
            take = hi[:cap]
            del hi[: len(take)]
            batch.extend(take)
        room = cap - len(batch)
        if room > 0 and no:
            # anti-starvation reserve: when the low lane's head already
            # waited past the bound, hold slots open so a saturated
            # normal lane cannot push it out forever
            reserve = 0
            if lo and len(no) >= room and (now - lo[0][2]) >= self.starvation_s:
                reserve = max(1, cap // 16)
                self.metrics.inc("ingest.lane.starvation.breaks")
            n_take = min(len(no), max(0, room - reserve))
            if n_take:
                batch.extend(no[:n_take])
                del no[:n_take]
            room = cap - len(batch)
        if room > 0 and lo:
            slo = self.slo
            if (
                not force
                and slo is not None
                and slo.defer_low(now - lo[0][2])
            ):
                # `defer` rung: the low lane sits this launch out so the
                # storm drains control/normal first (delayed, not lost)
                self.metrics.inc("slo.deferrals")
            else:
                take = lo[:room]
                del lo[: len(take)]
                batch.extend(take)
        return batch

    async def _settle(self, batch) -> None:
        seq, bsp = self._next_seq(batch)
        await self._finish(
            seq, batch,
            self.broker.adispatch_begin(
                [m for m, _, _, _ in batch], batch_span=bsp
            ),
            bsp,
        )

    def _next_seq(self, batch):
        """Assign the batch seq + record launch-side telemetry. Returns
        (seq, batch_span): the span is the fan-in node — every sampled
        publish in the batch LINKS into it (same seq key as the
        `ingest.launch` tracepoint), and it parents the device-step span.
        None when nothing in the batch is sampled."""
        n = len(batch)
        seq = self._seq
        self._seq += 1
        self.metrics.observe("ingest.batch.size", n)
        self.metrics.observe("ingest.batch.occupancy", n / self.max_batch)
        # waterfall `queue_wait` (observe/profiler.py): per-message
        # enqueue -> launch wait (window accumulation + lane queueing)
        now = time.perf_counter()
        self.metrics.observe_many(
            "profile.stage.queue_wait.seconds",
            [now - t0 for _, _, t0, _ in batch],
        )
        tp("ingest.launch", batch=seq, n=n)
        rec = getattr(self.broker, "spans", None)
        bsp = (
            rec.batch_begin(seq, [m for m, _, _, _ in batch], self.max_batch)
            if rec is not None
            else None
        )
        if bsp is not None and self.slo is not None:
            # controller state rides the batch span: a trace shows the
            # window/rung THIS batch launched under
            bsp.attrs["slo.window_us"] = round(self.slo.window_s * 1e6, 1)
            bsp.attrs["slo.rung"] = RUNG_NAMES[self.slo.rung]
        return seq, bsp

    async def _finish(self, seq: int, batch, aw, bsp=None) -> None:
        rec = getattr(self.broker, "spans", None)
        try:
            results = await aw
        except Exception as e:  # noqa: BLE001 — flusher must survive
            log.exception("batch dispatch failed; failing %d publishes", len(batch))
            self.metrics.inc("ingest.dispatch.errors")
            for m, fut, _, _ in batch:
                if not fut.done():
                    fut.set_exception(e)
                if rec is not None:
                    rec.publish_finish(
                        m.headers.get(TRACE_HEADER), 0, status="error"
                    )
            if rec is not None and bsp is not None:
                rec.finish(bsp, {"error": str(e)}, status="error")
            return
        now = time.perf_counter()
        lane_lats: List[List[float]] = [[], [], []]
        for (m, fut, t0, lane), n in zip(batch, results):
            if not fut.done():
                fut.set_result(n)
            lane_lats[lane].append(now - t0)
            if rec is not None:
                # settle the publish span by its context header (the
                # fan-in edge back to the publisher's trace)
                rec.publish_finish(m.headers.get(TRACE_HEADER), n)
        self.metrics.observe_many(
            "ingest.settle.seconds", [now - t0 for _, _, t0, _ in batch]
        )
        for lane, lats in enumerate(lane_lats):
            if lats:
                # per-lane tails: the chaos/bench gates assert the
                # control lane stays bounded while the low lane storms
                self.metrics.observe_many(LANE_SETTLE_SERIES[lane], lats)
        if rec is not None and bsp is not None:
            rec.finish(bsp)
        tp("ingest.settle", batch=seq, n=len(batch))

    def _engage_threshold(self) -> int:
        # below this pending count the device path won't engage anyway
        # (broker.dispatch_batch_folded falls back per-message), so waiting
        # a window would tax latency for zero batching gain
        return max(2, self.broker.router.min_tpu_batch)

    def _device_idle(self) -> bool:
        """Every in-flight dispatch's DEVICE work is done (their host
        fan-out may still be queued behind the FIFO settle)."""
        return all(pd.ready.done() for _, _, pd, _ in self._inflight)

    def _note_device_done(self, _fut=None) -> None:
        # done-callback on each launch's `ready`: stamp the moment the
        # pipeline's device side drained (idle-gap accounting)
        if self._device_idle():
            self._device_done_t = time.perf_counter()

    async def _run(self) -> None:
        while True:
            slo = self.slo
            if slo is not None:
                deg = getattr(self.broker, "degrade", None)
                self.window_s = slo.tick(
                    backlog=self._backlog(),
                    breaker_open=(
                        deg is not None and deg.device.state == OPEN
                    ),
                )
            if not self._inflight and not self._backlog():
                await self._event.wait()
            # one loop tick: every connection task that is ready to publish
            # gets to enqueue before we decide whether a window is worth it
            await asyncio.sleep(0)
            backlog = self._backlog()
            if (
                self.window_s > 0
                and not self._inflight
                and backlog >= self._engage_threshold()
                and backlog < self.max_batch
            ):
                # real concurrency: hold the window open to fill the batch
                t0 = time.perf_counter()
                await asyncio.sleep(self.window_s)
                self.metrics.observe(
                    "ingest.window.wait.seconds", time.perf_counter() - t0
                )
            # Launch rules. While a dispatch's DEVICE work is in flight,
            # only a FULL batch may launch: eagerly draining small batches
            # would multiply device round-trips and shrink per-dispatch
            # amortization (measured: e2e throughput collapsed ~3x when
            # the pipeline launched every pending dribble). But the
            # moment every in-flight dispatch's device work is DONE, a
            # PARTIAL batch launches too — batch N's host fan-out hasn't
            # run yet (FIFO settle below), so the partial overlaps it
            # with device compute instead of leaving the chip dark under
            # mid-load (the old full-batch/settle-boundary-only rule).
            batch: List = []
            if (
                not self._inflight
                or self._backlog() >= self.max_batch
                or (
                    self._backlog()
                    and len(self._inflight) < self.pipeline
                    and self._device_idle()
                )
            ):
                batch = self._take_batch(time.perf_counter())
            if batch:
                for lane, series in enumerate(LANE_DEPTH_SERIES):
                    self.metrics.gauge_set(
                        series, len(self._lane_list(lane))
                    )
                if self._device_done_t is not None:
                    self.metrics.observe(
                        "ingest.device.idle.seconds",
                        time.perf_counter() - self._device_done_t,
                    )
                    self._device_done_t = None
                # LAUNCH now (prepare + executor submit), settle later:
                # a full next batch's launch overlaps this one's
                # round-trip. Fan-out happens ONLY at settle
                # (pd.complete()), in FIFO order — pd.ready is the
                # side-effect-free pacing signal (per-publisher
                # cross-batch ordering).
                seq, bsp = self._next_seq(batch)
                try:
                    pd = self.broker.adispatch_begin(
                        [m for m, _, _, _ in batch], batch_span=bsp
                    )
                except Exception as e:  # noqa: BLE001 — flusher survives
                    log.exception("batch launch failed")
                    self.metrics.inc("ingest.launch.errors")
                    rec = getattr(self.broker, "spans", None)
                    for m, fut, _, _ in batch:
                        if not fut.done():
                            fut.set_exception(e)
                        if rec is not None:
                            rec.publish_finish(
                                m.headers.get(TRACE_HEADER), 0,
                                status="error",
                            )
                    if rec is not None and bsp is not None:
                        rec.finish(bsp, {"error": str(e)}, status="error")
                else:
                    self._inflight.append((seq, batch, pd, bsp))
                    self._device_done_t = None
                    pd.ready.add_done_callback(self._note_device_done)
                    self.metrics.gauge_set(
                        "ingest.pipeline.depth", len(self._inflight)
                    )
            if not self._inflight:
                if not self._backlog():
                    self._event.clear()
                elif not batch:
                    # everything pending is lane-deferred: nothing is
                    # launchable until the defer age bound releases it —
                    # bounded poll, never a busy spin
                    await asyncio.sleep(max(self.window_s, 0.001))
                continue
            if len(self._inflight) >= self.pipeline:
                seq, b, pd, bsp = self._inflight.popleft()
                await self._finish(seq, b, pd.complete(), bsp)
            elif not batch or not self._backlog():
                # dispatch in flight, nothing launchable: settle when
                # the device work completes OR re-check the moment new
                # publishes arrive (they may fill a full batch). The
                # event is cleared first so only NEW enqueues wake us —
                # otherwise a partial backlog would busy-spin this loop.
                self._event.clear()
                oldest_ready = self._inflight[0][2].ready
                ev = asyncio.ensure_future(self._event.wait())
                try:
                    await asyncio.wait(
                        {oldest_ready, ev},
                        return_when=asyncio.FIRST_COMPLETED,
                    )
                finally:
                    if not ev.done():
                        # retrieve the cancellation or the loop logs
                        # "Task was destroyed but it is pending" for
                        # every launch-in-flight/new-enqueue race.
                        # gather(return_exceptions) swallows EV's
                        # CancelledError but still re-raises OUR OWN
                        # task's cancellation (stop() must not hang)
                        ev.cancel()
                        await asyncio.gather(ev, return_exceptions=True)
                if oldest_ready.done():
                    if (
                        self._backlog()
                        and len(self._inflight) < self.pipeline
                        and self._device_idle()
                    ):
                        # device idle + launchable backlog: loop back so
                        # the partial LAUNCHES before this settle's host
                        # fan-out runs (the launch rule above fires on
                        # exactly this condition)
                        continue
                    seq, b, pd, bsp = self._inflight.popleft()
                    await self._finish(seq, b, pd.complete(), bsp)
