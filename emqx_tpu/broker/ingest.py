"""Ingest-side publish batch aggregation (adaptive batch window).

SURVEY.md §7 hard part (c): the device route path wants big batches, but a
publishing client wants low latency. This aggregator sits between the
channel's publish and the router: concurrent publishes from all connections
collect into one list, flushed when either `max_batch` messages are pending
or `window_us` has elapsed since the flusher woke — so a lone publisher
pays at most one window of added latency while a firehose fills batches
immediately and never sleeps.

The reference has no analog — its hot loop is per-message per-process
(emqx_broker.erl:204-215); this is the TPU-era replacement for that regime,
turning N concurrent publishes into one route_step kernel launch
(emqx_tpu.models.router_model.DeviceRouter).

Backpressure: `submit` awaits the flush result, so a publisher's PUBACK
reflects actual dispatch; the pending list is bounded only by connection
count x inflight windows, which the per-connection limiters already cap.

Flight recorder: every latency/throughput tradeoff this loop makes is
recorded into the broker's metrics (docs/observability.md) — batch size and
occupancy, window hold time, pipeline depth, per-message enqueue->settle
latency, and launch/dispatch failures — plus `ingest.launch`/`ingest.settle`
tracepoints keyed by batch seq for causal assertions in tests.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import List, Optional, Tuple

from emqx_tpu.broker.degrade import OPEN, IngestShed
from emqx_tpu.broker.message import Message
from emqx_tpu.broker.metrics import Metrics
from emqx_tpu.observe import faults as _faults
from emqx_tpu.observe.spans import TRACE_HEADER
from emqx_tpu.utils.tracepoints import tp

log = logging.getLogger("emqx_tpu.ingest")


class BatchIngest:
    def __init__(
        self,
        broker,
        max_batch: int = 4096,
        window_us: int = 1000,
        pipeline: int = 2,
        olp=None,
    ):
        self.broker = broker
        self.max_batch = max_batch
        self.window_s = window_us / 1e6
        # overload-protection signal (broker/olp.py): with the broker's
        # DegradeController attached, enqueues shed once the pending
        # backlog passes the shed bound while olp.is_overloaded() holds
        # or the device breaker is open — backpressure instead of
        # unbounded queue growth behind a broken fast path
        self.olp = olp
        # device dispatches in flight at once: batch N+1's table upload +
        # kernel launch overlaps batch N's readback round-trip (the
        # dominant per-batch wall when the chip sits behind a network
        # tunnel; on a local chip it overlaps host fan-out with device
        # compute). Settlement stays strictly FIFO so per-publisher
        # delivery order holds across batches.
        self.pipeline = max(1, pipeline)
        self.metrics: Metrics = getattr(broker, "metrics", None) or Metrics()
        # (msg, puback future, enqueue perf_counter timestamp)
        self._pending: List[Tuple[Message, asyncio.Future, float]] = []
        self._inflight: deque = deque()  # (seq, batch, pending, batch_span)
        self._event = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._seq = 0
        # perf_counter stamp of the moment the LAST in-flight dispatch's
        # device work completed (None = device busy or never launched);
        # the gap until the next launch is the ingest.device.idle series
        self._device_done_t: Optional[float] = None
        self.running = False

    def start(self) -> None:
        if self._task is None:
            self.running = True
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self.running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        # drain launched-but-unsettled batches first (FIFO), then
        # anything still pending, so no publisher hangs on shutdown
        while self._inflight:
            seq, batch, pd, bsp = self._inflight.popleft()
            await self._finish(seq, batch, pd.complete(), bsp)
        while self._pending:
            batch = self._pending[: self.max_batch]
            del self._pending[: self.max_batch]
            await self._settle(batch)

    def enqueue(self, msg: Message) -> asyncio.Future:
        """Enqueue one folded message; the future resolves with its
        delivery count when the batch flushes.

        Shed gate (docs/robustness.md): while the broker is overloaded
        (olp) or the device breaker is open, a backlog past the shed
        bound refuses new enqueues with `IngestShed` on the returned
        future — the publisher's PUBACK fails (QoS>=1 clients retry)
        instead of the pending list growing without bound behind a
        degraded pipeline."""
        act = _faults.hit("ingest.enqueue")  # raise -> publisher's task
        fut = asyncio.get_running_loop().create_future()
        shed = act == "drop"
        deg = getattr(self.broker, "degrade", None)
        if (
            not shed
            and deg is not None
            and len(self._pending)
            >= deg.shed_queue_batches * self.max_batch
            and (
                (self.olp is not None and self.olp.is_overloaded())
                or deg.device.state == OPEN
            )
        ):
            shed = True
        if shed:
            self.metrics.inc("ingest.shed")
            fut.set_exception(
                IngestShed("ingest backlog shed (overload/degraded)")
            )
            return fut
        self._pending.append((msg, fut, time.perf_counter()))
        self._event.set()
        return fut

    async def submit(self, msg: Message) -> int:
        return await self.enqueue(msg)

    async def _settle(self, batch) -> None:
        seq, bsp = self._next_seq(batch)
        await self._finish(
            seq, batch,
            self.broker.adispatch_begin(
                [m for m, _, _ in batch], batch_span=bsp
            ),
            bsp,
        )

    def _next_seq(self, batch):
        """Assign the batch seq + record launch-side telemetry. Returns
        (seq, batch_span): the span is the fan-in node — every sampled
        publish in the batch LINKS into it (same seq key as the
        `ingest.launch` tracepoint), and it parents the device-step span.
        None when nothing in the batch is sampled."""
        n = len(batch)
        seq = self._seq
        self._seq += 1
        self.metrics.observe("ingest.batch.size", n)
        self.metrics.observe("ingest.batch.occupancy", n / self.max_batch)
        tp("ingest.launch", batch=seq, n=n)
        rec = getattr(self.broker, "spans", None)
        bsp = (
            rec.batch_begin(seq, [m for m, _, _ in batch], self.max_batch)
            if rec is not None
            else None
        )
        return seq, bsp

    async def _finish(self, seq: int, batch, aw, bsp=None) -> None:
        rec = getattr(self.broker, "spans", None)
        try:
            results = await aw
        except Exception as e:  # noqa: BLE001 — flusher must survive
            log.exception("batch dispatch failed; failing %d publishes", len(batch))
            self.metrics.inc("ingest.dispatch.errors")
            for m, fut, _ in batch:
                if not fut.done():
                    fut.set_exception(e)
                if rec is not None:
                    rec.publish_finish(
                        m.headers.get(TRACE_HEADER), 0, status="error"
                    )
            if rec is not None and bsp is not None:
                rec.finish(bsp, {"error": str(e)}, status="error")
            return
        now = time.perf_counter()
        for (m, fut, _), n in zip(batch, results):
            if not fut.done():
                fut.set_result(n)
            if rec is not None:
                # settle the publish span by its context header (the
                # fan-in edge back to the publisher's trace)
                rec.publish_finish(m.headers.get(TRACE_HEADER), n)
        self.metrics.observe_many(
            "ingest.settle.seconds", [now - t0 for _, _, t0 in batch]
        )
        if rec is not None and bsp is not None:
            rec.finish(bsp)
        tp("ingest.settle", batch=seq, n=len(batch))

    def _engage_threshold(self) -> int:
        # below this pending count the device path won't engage anyway
        # (broker.dispatch_batch_folded falls back per-message), so waiting
        # a window would tax latency for zero batching gain
        return max(2, self.broker.router.min_tpu_batch)

    def _device_idle(self) -> bool:
        """Every in-flight dispatch's DEVICE work is done (their host
        fan-out may still be queued behind the FIFO settle)."""
        return all(pd.ready.done() for _, _, pd, _ in self._inflight)

    def _note_device_done(self, _fut=None) -> None:
        # done-callback on each launch's `ready`: stamp the moment the
        # pipeline's device side drained (idle-gap accounting)
        if self._device_idle():
            self._device_done_t = time.perf_counter()

    async def _run(self) -> None:
        while True:
            if not self._inflight and not self._pending:
                await self._event.wait()
            # one loop tick: every connection task that is ready to publish
            # gets to enqueue before we decide whether a window is worth it
            await asyncio.sleep(0)
            if (
                self.window_s > 0
                and not self._inflight
                and len(self._pending) >= self._engage_threshold()
                and len(self._pending) < self.max_batch
            ):
                # real concurrency: hold the window open to fill the batch
                t0 = time.perf_counter()
                await asyncio.sleep(self.window_s)
                self.metrics.observe(
                    "ingest.window.wait.seconds", time.perf_counter() - t0
                )
            # Launch rules. While a dispatch's DEVICE work is in flight,
            # only a FULL batch may launch: eagerly draining small batches
            # would multiply device round-trips and shrink per-dispatch
            # amortization (measured: e2e throughput collapsed ~3x when
            # the pipeline launched every pending dribble). But the
            # moment every in-flight dispatch's device work is DONE, a
            # PARTIAL batch launches too — batch N's host fan-out hasn't
            # run yet (FIFO settle below), so the partial overlaps it
            # with device compute instead of leaving the chip dark under
            # mid-load (the old full-batch/settle-boundary-only rule).
            batch: List = []
            if (
                not self._inflight
                or len(self._pending) >= self.max_batch
                or (
                    self._pending
                    and len(self._inflight) < self.pipeline
                    and self._device_idle()
                )
            ):
                batch = self._pending[: self.max_batch]
                del self._pending[: self.max_batch]
            if batch:
                if self._device_done_t is not None:
                    self.metrics.observe(
                        "ingest.device.idle.seconds",
                        time.perf_counter() - self._device_done_t,
                    )
                    self._device_done_t = None
                # LAUNCH now (prepare + executor submit), settle later:
                # a full next batch's launch overlaps this one's
                # round-trip. Fan-out happens ONLY at settle
                # (pd.complete()), in FIFO order — pd.ready is the
                # side-effect-free pacing signal (per-publisher
                # cross-batch ordering).
                seq, bsp = self._next_seq(batch)
                try:
                    pd = self.broker.adispatch_begin(
                        [m for m, _, _ in batch], batch_span=bsp
                    )
                except Exception as e:  # noqa: BLE001 — flusher survives
                    log.exception("batch launch failed")
                    self.metrics.inc("ingest.launch.errors")
                    rec = getattr(self.broker, "spans", None)
                    for m, fut, _ in batch:
                        if not fut.done():
                            fut.set_exception(e)
                        if rec is not None:
                            rec.publish_finish(
                                m.headers.get(TRACE_HEADER), 0,
                                status="error",
                            )
                    if rec is not None and bsp is not None:
                        rec.finish(bsp, {"error": str(e)}, status="error")
                else:
                    self._inflight.append((seq, batch, pd, bsp))
                    self._device_done_t = None
                    pd.ready.add_done_callback(self._note_device_done)
                    self.metrics.gauge_set(
                        "ingest.pipeline.depth", len(self._inflight)
                    )
            if not self._inflight:
                if not self._pending:
                    self._event.clear()
                continue
            if len(self._inflight) >= self.pipeline:
                seq, b, pd, bsp = self._inflight.popleft()
                await self._finish(seq, b, pd.complete(), bsp)
            elif not batch or not self._pending:
                # dispatch in flight, nothing launchable: settle when
                # the device work completes OR re-check the moment new
                # publishes arrive (they may fill a full batch). The
                # event is cleared first so only NEW enqueues wake us —
                # otherwise a partial backlog would busy-spin this loop.
                self._event.clear()
                oldest_ready = self._inflight[0][2].ready
                ev = asyncio.ensure_future(self._event.wait())
                try:
                    await asyncio.wait(
                        {oldest_ready, ev},
                        return_when=asyncio.FIRST_COMPLETED,
                    )
                finally:
                    if not ev.done():
                        # retrieve the cancellation or the loop logs
                        # "Task was destroyed but it is pending" for
                        # every launch-in-flight/new-enqueue race.
                        # gather(return_exceptions) swallows EV's
                        # CancelledError but still re-raises OUR OWN
                        # task's cancellation (stop() must not hang)
                        ev.cancel()
                        await asyncio.gather(ev, return_exceptions=True)
                if oldest_ready.done():
                    if (
                        self._pending
                        and len(self._inflight) < self.pipeline
                        and self._device_idle()
                    ):
                        # device idle + launchable backlog: loop back so
                        # the partial LAUNCHES before this settle's host
                        # fan-out runs (the launch rule above fires on
                        # exactly this condition)
                        continue
                    seq, b, pd, bsp = self._inflight.popleft()
                    await self._finish(seq, b, pd.complete(), bsp)
