"""Ingest-side publish batch aggregation (adaptive batch window).

SURVEY.md §7 hard part (c): the device route path wants big batches, but a
publishing client wants low latency. This aggregator sits between the
channel's publish and the router: concurrent publishes from all connections
collect into one list, flushed when either `max_batch` messages are pending
or `window_us` has elapsed since the flusher woke — so a lone publisher
pays at most one window of added latency while a firehose fills batches
immediately and never sleeps.

The reference has no analog — its hot loop is per-message per-process
(emqx_broker.erl:204-215); this is the TPU-era replacement for that regime,
turning N concurrent publishes into one route_step kernel launch
(emqx_tpu.models.router_model.DeviceRouter).

Backpressure: `submit` awaits the flush result, so a publisher's PUBACK
reflects actual dispatch; the pending list is bounded only by connection
count x inflight windows, which the per-connection limiters already cap.
"""

from __future__ import annotations

import asyncio
import logging
from collections import deque
from typing import List, Optional, Tuple

from emqx_tpu.broker.message import Message

log = logging.getLogger("emqx_tpu.ingest")


class BatchIngest:
    def __init__(
        self,
        broker,
        max_batch: int = 4096,
        window_us: int = 1000,
        pipeline: int = 2,
    ):
        self.broker = broker
        self.max_batch = max_batch
        self.window_s = window_us / 1e6
        # device dispatches in flight at once: batch N+1's table upload +
        # kernel launch overlaps batch N's readback round-trip (the
        # dominant per-batch wall when the chip sits behind a network
        # tunnel; on a local chip it overlaps host fan-out with device
        # compute). Settlement stays strictly FIFO so per-publisher
        # delivery order holds across batches.
        self.pipeline = max(1, pipeline)
        self._pending: List[Tuple[Message, asyncio.Future]] = []
        self._inflight: deque = deque()  # (batch, awaitable)
        self._event = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self.running = False

    def start(self) -> None:
        if self._task is None:
            self.running = True
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self.running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        # drain launched-but-unsettled batches first (FIFO), then
        # anything still pending, so no publisher hangs on shutdown
        while self._inflight:
            batch, pd = self._inflight.popleft()
            await self._finish(batch, pd.complete())
        while self._pending:
            batch = self._pending[: self.max_batch]
            del self._pending[: self.max_batch]
            await self._settle(batch)

    def enqueue(self, msg: Message) -> asyncio.Future:
        """Enqueue one folded message; the future resolves with its
        delivery count when the batch flushes."""
        fut = asyncio.get_running_loop().create_future()
        self._pending.append((msg, fut))
        self._event.set()
        return fut

    async def submit(self, msg: Message) -> int:
        return await self.enqueue(msg)

    async def _settle(self, batch: List[Tuple[Message, asyncio.Future]]) -> None:
        await self._finish(
            batch, self.broker.adispatch_begin([m for m, _ in batch])
        )

    async def _finish(self, batch, aw) -> None:
        try:
            results = await aw
        except Exception as e:  # noqa: BLE001 — flusher must survive
            log.exception("batch dispatch failed; failing %d publishes", len(batch))
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)
            return
        for (_, fut), n in zip(batch, results):
            if not fut.done():
                fut.set_result(n)

    def _engage_threshold(self) -> int:
        # below this pending count the device path won't engage anyway
        # (broker.dispatch_batch_folded falls back per-message), so waiting
        # a window would tax latency for zero batching gain
        return max(2, self.broker.router.min_tpu_batch)

    async def _run(self) -> None:
        while True:
            if not self._inflight and not self._pending:
                await self._event.wait()
            # one loop tick: every connection task that is ready to publish
            # gets to enqueue before we decide whether a window is worth it
            await asyncio.sleep(0)
            if (
                self.window_s > 0
                and not self._inflight
                and len(self._pending) >= self._engage_threshold()
                and len(self._pending) < self.max_batch
            ):
                # real concurrency: hold the window open to fill the batch
                await asyncio.sleep(self.window_s)
            # while a dispatch is in flight, only launch another for a
            # FULL batch: eagerly draining small batches would multiply
            # device round-trips and shrink per-dispatch amortization
            # (measured: e2e throughput collapsed ~3x when the pipeline
            # launched every pending dribble); a partial batch keeps
            # accumulating until the oldest dispatch settles
            batch: List = []
            if not self._inflight or len(self._pending) >= self.max_batch:
                batch = self._pending[: self.max_batch]
                del self._pending[: self.max_batch]
            if batch:
                # LAUNCH now (prepare + executor submit), settle later:
                # a full next batch's launch overlaps this one's
                # round-trip. Fan-out happens ONLY at settle
                # (pd.complete()), in FIFO order — pd.ready is the
                # side-effect-free pacing signal (per-publisher
                # cross-batch ordering).
                try:
                    pd = self.broker.adispatch_begin(
                        [m for m, _ in batch]
                    )
                except Exception as e:  # noqa: BLE001 — flusher survives
                    log.exception("batch launch failed")
                    for _, fut in batch:
                        if not fut.done():
                            fut.set_exception(e)
                else:
                    self._inflight.append((batch, pd))
            if not self._inflight:
                if not self._pending:
                    self._event.clear()
                continue
            if len(self._inflight) >= self.pipeline:
                b, pd = self._inflight.popleft()
                await self._finish(b, pd.complete())
            elif not batch or not self._pending:
                # dispatch in flight, nothing launchable: settle when
                # the device work completes OR re-check the moment new
                # publishes arrive (they may fill a full batch). The
                # event is cleared first so only NEW enqueues wake us —
                # otherwise a partial backlog would busy-spin this loop.
                self._event.clear()
                oldest_ready = self._inflight[0][1].ready
                ev = asyncio.ensure_future(self._event.wait())
                try:
                    await asyncio.wait(
                        {oldest_ready, ev},
                        return_when=asyncio.FIRST_COMPLETED,
                    )
                finally:
                    if not ev.done():
                        ev.cancel()
                if oldest_ready.done():
                    b, pd = self._inflight.popleft()
                    await self._finish(b, pd.complete())
