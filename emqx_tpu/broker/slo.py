"""SLO-driven adaptive batching: tail latency as a controlled variable.

The ingest window used to be a fixed policy (`window_us=1000`) with one
binary escape hatch — shed everything past `shed_queue_batches *
max_batch` while overloaded. Production traffic is not fixed:
"Benchmarking Message Brokers for IoT Edge Computing" (PAPERS.md) shows
brokers differentiate on the latency-vs-throughput *frontier*, not peak
RPS. This module is the continuous-batching controller (the
inference-server idiom) that turns the window into a controlled
variable:

- **feedback signal**: the PR 1 `ingest.settle.seconds` histogram —
  each evaluation window diffs the cumulative buckets and computes the
  p99 of ONLY the publishes that settled since the last look;
- **control law**: hold the configured p99 target with hysteresis.
  Idle traffic decays the window toward `min_window_us` (immediate
  partial launches); sustained violations widen it toward
  `max_window_us` (deep batches amortize launches AND slow intake —
  graded backpressure the publisher feels as latency, not loss);
  readings inside the hysteresis band change nothing (no oscillation
  between flush cycles);
- **backpressure ladder** (docs/robustness.md): violations escalate
  `normal -> widen -> defer -> shed` with `ladder_patience` consecutive
  readings per rung, and de-escalate the same way. `widen` deepens
  batches; `defer` parks the low-priority lane (QoS0 firehose,
  retained-storm replays) so control traffic launches first; `shed`
  refuses new low-priority enqueues past the queue bound — the old
  binary `IngestShed` cliff is now the LAST rung, not the only one;
- **degrade integration**: an open device breaker (broker/degrade.py)
  forces the ladder to at least `widen` — the CPU fallback path wants
  deep batches and slowed intake — but shedding still requires walking
  the remaining rungs. Breaker-open never jumps straight to drops.

Priority lanes (broker/ingest.py): `control` (QoS2 control flow, $SYS,
session-critical traffic) > `normal` (QoS1) > `low` (QoS0 firehose,
retained-storm replays). The flusher assembles batches in lane order
with an anti-starvation reserve, so a storm can delay the low lane but
never a PUBREL behind it — and the low lane is never starved outright.

Controller state rides `slo.*` gauges/counters and batch-span attrs;
`SloViolationWatch` (observe/alarm.py) raises the level-triggered
`slo_p99_violation` alarm on sustained target misses.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, List, Optional, Tuple

log = logging.getLogger("emqx_tpu.slo")

# priority lanes (broker/ingest.py BatchIngest)
LANE_CONTROL = 0
LANE_NORMAL = 1
LANE_LOW = 2
LANE_NAMES = ("control", "normal", "low")

# backpressure ladder rungs, in escalation order (docs/robustness.md)
RUNG_NORMAL = 0
RUNG_WIDEN = 1
RUNG_DEFER = 2
RUNG_SHED = 3
RUNG_NAMES = ("normal", "widen", "defer", "shed")


def delta_percentile(
    prev: Optional[Dict], cur: Optional[Dict], q: float
) -> Tuple[float, int]:
    """Percentile of the observations BETWEEN two cumulative histogram
    snapshots (`Histogram.snapshot()` shape). Returns (value, samples);
    (0.0, 0) when nothing landed. Interpolates inside the landing bucket
    like `Histogram.percentile`; a quantile in the +Inf overflow bucket
    reports the last finite bound."""
    if cur is None:
        return 0.0, 0
    cur_b = cur["buckets"]
    prev_b = prev["buckets"] if prev is not None else None
    n = cur["count"] - (prev["count"] if prev is not None else 0)
    if n <= 0:
        return 0.0, 0
    rank = q * n
    cum = 0
    lo = 0.0
    for i, (le, c_cum) in enumerate(cur_b):
        p_cum = prev_b[i][1] if prev_b is not None else 0
        d_cum = c_cum - p_cum
        if d_cum > cum:
            bucket = d_cum - cum
            prev_cum = cum
            cum = d_cum
            if cum >= rank:
                if le == float("inf"):
                    return lo, n
                frac = (rank - prev_cum) / bucket if bucket else 1.0
                return lo + (le - lo) * min(max(frac, 0.0), 1.0), n
        if le != float("inf"):
            lo = le
    return lo, n


class SloController:
    """Adapts `BatchIngest`'s window each flush cycle to hold a p99
    target, and owns the graded backpressure ladder.

    Single-writer: loop (BatchIngest._run drives `tick`; lane/shed
    queries run on the loop too). All knobs map 1:1 to `slo.*` config
    keys (config/schema.py SloConfig)."""

    def __init__(
        self,
        metrics=None,
        *,
        target_p99_ms: float = 5.0,
        min_window_us: int = 0,
        max_window_us: int = 20_000,
        initial_window_us: int = 1000,
        eval_interval_s: float = 0.05,
        min_samples: int = 32,
        gain: float = 0.25,
        hysteresis: float = 0.7,
        ladder_patience: int = 3,
        defer_max_s: float = 0.25,
        starvation_s: float = 0.05,
        shed_hard_mult: float = 4.0,
        series: str = "ingest.settle.seconds",
        olp=None,
        spans=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.metrics = metrics
        self.target_p99_ms = float(target_p99_ms)
        self.min_window_s = max(0.0, min_window_us / 1e6)
        self.max_window_s = max(self.min_window_s, max_window_us / 1e6)
        self.eval_interval_s = max(0.001, float(eval_interval_s))
        self.min_samples = max(1, int(min_samples))
        self.gain = min(0.9, max(0.01, float(gain)))
        self.hysteresis = min(1.0, max(0.0, float(hysteresis)))
        self.ladder_patience = max(1, int(ladder_patience))
        self.defer_max_s = max(0.0, float(defer_max_s))
        self.starvation_s = max(0.0, float(starvation_s))
        self.shed_hard_mult = max(1.0, float(shed_hard_mult))
        self.series = series
        self.olp = olp
        self.spans = spans
        self.clock = clock
        self.window_s = min(
            self.max_window_s, max(self.min_window_s, initial_window_us / 1e6)
        )
        self.rung = RUNG_NORMAL
        self.last_p99_ms: Optional[float] = None
        self.last_samples = 0
        self._viol = 0  # consecutive violating evaluations
        self._clear = 0  # consecutive clear evaluations
        self._last_eval: Optional[float] = None
        self._snap: Optional[Dict] = None
        if metrics is not None:
            metrics.gauge_set("slo.p99.target_ms", self.target_p99_ms)
            metrics.gauge_set("slo.window_us", round(self.window_s * 1e6, 1))
            metrics.gauge_set("slo.ladder.rung", self.rung)

    # -- control loop -------------------------------------------------------
    def tick(
        self,
        backlog: int = 0,
        breaker_open: bool = False,
        now: Optional[float] = None,
    ) -> float:
        """One flusher-cycle look: returns the window (seconds) to use
        for THIS cycle. Internally rate-limited to `eval_interval_s` —
        calling it every loop iteration is the intended shape."""
        now = self.clock() if now is None else now
        if breaker_open and self.rung < RUNG_WIDEN:
            # degrade-ladder integration: an open breaker widens the
            # window BEFORE anything sheds — the CPU fallback wants deep
            # batches, and slowed intake is backpressure without loss
            self._set_rung(RUNG_WIDEN, "breaker_open")
            self._widen()
        if self._last_eval is None:
            self._last_eval = now
            self._snap = self._snapshot()
            return self.window_s
        if now - self._last_eval < self.eval_interval_s:
            return self.window_s
        self._last_eval = now
        cur = self._snapshot()
        p99_s, n = delta_percentile(self._snap, cur, 0.99)
        self._snap = cur
        p99_ms = p99_s * 1e3
        self.last_p99_ms = p99_ms if n else None
        self.last_samples = n
        m = self.metrics
        if m is not None:
            m.inc("slo.eval.windows")
            if n:
                m.gauge_set("slo.p99.observed_ms", round(p99_ms, 3))
        overloaded = self.olp is not None and self.olp.is_overloaded()
        if n < self.min_samples and not (overloaded or breaker_open):
            # too little settled traffic to judge the tail: relax toward
            # immediate launches (a lone publisher must not pay a storm-
            # deep window) and walk the ladder back down
            self._relax(idle=backlog == 0)
        elif (n >= self.min_samples and p99_ms > self.target_p99_ms) or (
            overloaded or breaker_open
        ):
            if n >= self.min_samples and p99_ms > self.target_p99_ms:
                reason = "p99_miss"
            elif breaker_open:
                reason = "breaker_open"
            else:
                reason = "olp_overload"
            self._violation(reason)
        elif p99_ms <= self.target_p99_ms * self.hysteresis:
            self._cleared()
        # else: inside the hysteresis band — hold everything (the
        # no-oscillation guarantee between flush cycles)
        if m is not None:
            m.gauge_set("slo.window_us", round(self.window_s * 1e6, 1))
        return self.window_s

    def _snapshot(self) -> Optional[Dict]:
        if self.metrics is None:
            return None
        h = self.metrics.histogram(self.series)
        return h.snapshot() if h is not None else None

    def _violation(self, reason: str) -> None:
        self._viol += 1
        self._clear = 0
        if self.metrics is not None:
            self.metrics.inc("slo.violations")
        if self.rung == RUNG_NORMAL:
            self._set_rung(RUNG_WIDEN, reason)
        elif self._viol >= self.ladder_patience and self.rung < RUNG_SHED:
            self._set_rung(self.rung + 1, reason)
            self._viol = 0
        self._widen()

    def _cleared(self) -> None:
        self._clear += 1
        self._viol = 0
        if self._clear >= self.ladder_patience:
            self._clear = 0
            if self.rung > RUNG_NORMAL:
                self._set_rung(self.rung - 1, "recovered")
        self._narrow()

    def _relax(self, idle: bool) -> None:
        if idle:
            self._set_window(self.min_window_s)
        else:
            self._narrow()
        self._viol = 0
        self._clear += 1
        if self._clear >= self.ladder_patience and self.rung > RUNG_NORMAL:
            self._clear = 0
            self._set_rung(self.rung - 1, "drained")

    def _widen(self) -> None:
        base = self.window_s if self.window_s > 0 else max(
            self.min_window_s, 1e-4
        )
        self._set_window(min(self.max_window_s, base * (1.0 + self.gain)))

    def _narrow(self) -> None:
        self._set_window(
            max(self.min_window_s, self.window_s * (1.0 - self.gain))
        )

    def _set_window(self, w: float) -> None:
        if abs(w - self.window_s) < 1e-9:
            return
        self.window_s = w
        if self.metrics is not None:
            self.metrics.inc("slo.adjustments")

    def _set_rung(self, rung: int, reason: str) -> None:
        old, self.rung = self.rung, rung
        if old == rung:
            return
        self._viol = 0
        self._clear = 0
        log.warning(
            "slo ladder: %s -> %s (%s)",
            RUNG_NAMES[old], RUNG_NAMES[rung], reason,
        )
        if self.metrics is not None:
            self.metrics.gauge_set("slo.ladder.rung", rung)
        rec = self.spans
        if rec is not None:
            # the causal record of WHY subsequent batches deepened,
            # deferred, or shed (sibling of degrade.transition)
            sp = rec.start(
                "slo.transition",
                attrs={
                    "from": RUNG_NAMES[old],
                    "to": RUNG_NAMES[rung],
                    "reason": reason,
                },
            )
            rec.finish(sp)

    # -- ladder queries (BatchIngest / RetainedStormFeed) -------------------
    def defer_low(self, head_age_s: float) -> bool:
        """Should the low-priority lane sit this launch out? True on the
        `defer` rung and above — but never past `defer_max_s`, the
        anti-starvation bound (deferred is delayed, not dropped)."""
        return self.rung >= RUNG_DEFER and head_age_s < self.defer_max_s

    def shed(self, lane: int, backlog: int, bound: int) -> bool:
        """Graded admission (the last rung). Control traffic NEVER
        sheds; low sheds at the queue bound on the `shed` rung, normal
        only at twice the bound; `shed_hard_mult * bound` is the
        absolute safety valve at any rung (a wedged flusher must not
        queue unbounded)."""
        if lane == LANE_CONTROL:
            return False
        if backlog >= bound * self.shed_hard_mult:
            return True
        if self.rung < RUNG_SHED:
            return False
        return backlog >= (bound if lane == LANE_LOW else 2 * bound)

    # -- observability ------------------------------------------------------
    def to_json(self) -> Dict:
        return {
            "window_us": round(self.window_s * 1e6, 1),
            "min_window_us": round(self.min_window_s * 1e6, 1),
            "max_window_us": round(self.max_window_s * 1e6, 1),
            "target_p99_ms": self.target_p99_ms,
            "observed_p99_ms": (
                round(self.last_p99_ms, 3)
                if self.last_p99_ms is not None
                else None
            ),
            "observed_samples": self.last_samples,
            "rung": self.rung,
            "rung_name": RUNG_NAMES[self.rung],
        }


__all__: List[str] = [
    "LANE_CONTROL",
    "LANE_NORMAL",
    "LANE_LOW",
    "LANE_NAMES",
    "RUNG_NORMAL",
    "RUNG_WIDEN",
    "RUNG_DEFER",
    "RUNG_SHED",
    "RUNG_NAMES",
    "SloController",
    "delta_percentile",
]
