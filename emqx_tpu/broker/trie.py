"""Authoritative CPU topic trie for wildcard filters.

This is the *semantic reference* in the new framework: the TPU NFA matcher
(`emqx_tpu.ops.nfa` / `emqx_tpu.ops.matcher`) is differentially tested against
it, and the broker falls back to it for pathological inputs (topics deeper
than the compiled level budget).

Capability parity with the reference trie (apps/emqx/src/emqx_trie.erl:29-35,
271-333): insert/delete of wildcard filters with prefix reference counting,
and `match(topic)` returning every stored filter matching the topic, with

- ``+`` matching exactly one level,
- ``#`` matching any suffix including the empty one (``a/#`` matches ``a``),
- root-level ``+``/``#`` never matching ``$``-prefixed topics
  (emqx_trie.erl:271-278).

Unlike the reference, which stores prefix-counted rows in a replicated mnesia
table (because match *and* update both walk ETS), this trie is a plain linked
node structure: the CPU side only needs single-key updates and occasional
fallback matches — batch matching happens on the TPU tables compiled from the
same insert/delete stream.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional

from emqx_tpu.ops import topics as T


class _Node:
    __slots__ = ("children", "terminal", "refcount")

    def __init__(self) -> None:
        self.children: Dict[str, _Node] = {}
        # terminal > 0 => a filter ends here (refcount of identical inserts)
        self.terminal: int = 0
        # number of filters stored at or below this node
        self.refcount: int = 0


class TopicTrie:
    """Counted topic trie over level words; stores any topic filter."""

    def __init__(self) -> None:
        self._root = _Node()
        self._size = 0  # distinct filters

    def __len__(self) -> int:
        return self._size

    def is_empty(self) -> bool:
        return self._size == 0

    def insert(self, filter_: str) -> bool:
        """Insert a filter; returns True if it was newly added."""
        node = self._root
        path = [node]
        for w in T.words(filter_):
            node = node.children.setdefault(w, _Node())
            path.append(node)
        new = node.terminal == 0
        node.terminal += 1
        if new:
            for n in path:
                n.refcount += 1
            self._size += 1
        return new

    def delete(self, filter_: str) -> bool:
        """Remove a filter; returns True if it existed (fully removed)."""
        ws = T.words(filter_)
        path: List[tuple[_Node, str]] = []
        node = self._root
        for w in ws:
            child = node.children.get(w)
            if child is None:
                return False
            path.append((node, w))
            node = child
        if node.terminal == 0:
            return False
        node.terminal -= 1
        if node.terminal > 0:
            return False
        self._size -= 1
        self._root.refcount -= 1
        for parent, w in path:
            child = parent.children[w]
            child.refcount -= 1
            if child.refcount == 0:
                del parent.children[w]
        return True

    def has(self, filter_: str) -> bool:
        node = self._root
        for w in T.words(filter_):
            node = node.children.get(w)
            if node is None:
                return False
        return node.terminal > 0

    def filters(self) -> Iterator[str]:
        """Iterate all stored filters (depth-first)."""

        def walk(node: _Node, prefix: List[str]) -> Iterator[str]:
            if node.terminal:
                yield "/".join(prefix)
            for w, child in node.children.items():
                prefix.append(w)
                yield from walk(child, prefix)
                prefix.pop()

        for w, child in self._root.children.items():
            yield from walk(child, [w])

    def match(self, topic: str) -> List[str]:
        """All stored filters matching `topic` (exact filters included)."""
        ws = T.words(topic)
        acc: List[str] = []
        dollar = topic.startswith("$")

        def walk(node: _Node, i: int, prefix: List[str], root_level: bool) -> None:
            if i == len(ws):
                if node.terminal:
                    acc.append("/".join(prefix))
                hchild = node.children.get("#")
                if hchild is not None and hchild.terminal and not (root_level and dollar):
                    acc.append("/".join(prefix + ["#"]))
                return
            hchild = node.children.get("#")
            if hchild is not None and hchild.terminal and not (root_level and dollar):
                acc.append("/".join(prefix + ["#"]))
            w = ws[i]
            # children named '+'/'#' are wildcard branches, not literals: a
            # literal '+'/'#' character in a (malformed) topic must not take
            # them as an exact-word step (the reference cannot confuse the
            # two: its wildcard branch keys are atoms, topic words binaries)
            lit = node.children.get(w) if w not in ("+", "#") else None
            if lit is not None:
                prefix.append(w)
                walk(lit, i + 1, prefix, False)
                prefix.pop()
            if not (root_level and dollar):
                plus = node.children.get("+")
                if plus is not None:
                    prefix.append("+")
                    walk(plus, i + 1, prefix, False)
                    prefix.pop()

        walk(self._root, 0, [], True)
        return acc
