"""Authentication chain (reference: apps/emqx/src/emqx_authentication.erl +
apps/emqx_authn providers, SURVEY.md §2.2).

Chain-of-providers on the 'client.authenticate' hookpoint: each provider
returns 'ignore' (next provider), 'ok' (allow, stop), or 'deny' (reject,
stop). Built-in providers:

- `BuiltinDatabase`: in-memory credential store with pbkdf2/sha256/plain
  password hashing (the emqx_authn_mnesia analog; bcrypt is not available
  in this image, pbkdf2 is the strong default)
- `JwtAuth`: HS256 JWT verification from the password field
  (emqx_authn_jwt analog, hand-rolled HMAC — no external jwt dep)
- HTTP/SQL/LDAP provider slots follow the same Provider protocol and are
  async-backed (future work; the chain API already accommodates them).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.mqtt import packet as pkt

IGNORE, OK, DENY = "ignore", "ok", "deny"


class Provider:
    def authenticate(self, client_info: Dict, credentials: Dict) -> Tuple[str, Optional[int]]:
        """-> (ignore|ok|deny, reason_code|None)"""
        raise NotImplementedError

    async def authenticate_async(
        self, client_info: Dict, credentials: Dict
    ) -> Tuple[str, Optional[int]]:
        """Async variant — external-backend providers (HTTP/JWKS) override
        this; the default defers to the sync implementation."""
        return self.authenticate(client_info, credentials)


def _hash_password(password: bytes, algo: str, salt: bytes, iterations: int = 10000) -> bytes:
    if algo == "plain":
        return password
    if algo == "sha256":
        return hashlib.sha256(salt + password).digest()
    if algo == "pbkdf2":
        return hashlib.pbkdf2_hmac("sha256", password, salt, iterations)
    raise ValueError(f"unknown hash algo {algo}")


@dataclass
class _Cred:
    algo: str
    salt: bytes
    phash: bytes
    is_superuser: bool = False


class BuiltinDatabase(Provider):
    """Username/clientid -> salted password hash store."""

    def __init__(self, user_id_type: str = "username", algo: str = "pbkdf2"):
        assert user_id_type in ("username", "clientid")
        self.user_id_type = user_id_type
        self.algo = algo
        self._users: Dict[str, _Cred] = {}

    def add_user(self, user_id: str, password: str, is_superuser: bool = False) -> None:
        salt = os.urandom(16)
        self._users[user_id] = _Cred(
            self.algo,
            salt,
            _hash_password(password.encode(), self.algo, salt),
            is_superuser,
        )

    def delete_user(self, user_id: str) -> bool:
        return self._users.pop(user_id, None) is not None

    def users(self) -> List[str]:
        return list(self._users)

    def authenticate(self, client_info, credentials):
        uid = (
            client_info.get("username")
            if self.user_id_type == "username"
            else client_info.get("client_id")
        )
        if uid is None:
            # anonymous client: no opinion — the chain's allow_anonymous
            # policy decides, not this provider
            return IGNORE, None
        cred = self._users.get(uid)
        if cred is None:
            return IGNORE, None
        password = credentials.get("password") or b""
        good = hmac.compare_digest(
            _hash_password(password, cred.algo, cred.salt), cred.phash
        )
        if good:
            client_info["is_superuser"] = cred.is_superuser
            return OK, None
        return DENY, pkt.RC_BAD_USERNAME_OR_PASSWORD


class JwtAuth(Provider):
    """HS256 JWT in the password field; claims may pin clientid/username."""

    def __init__(self, secret: bytes, verify_claims: Optional[Dict[str, str]] = None):
        self.secret = secret
        # claim -> expected value with ${clientid}/${username} placeholders
        self.verify_claims = verify_claims or {}

    @staticmethod
    def _b64d(s: str) -> bytes:
        return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))

    def authenticate(self, client_info, credentials):
        token = credentials.get("password")
        if not token:
            return IGNORE, None
        try:
            parts = token.decode().split(".")
            if len(parts) != 3:
                return IGNORE, None
            header = json.loads(self._b64d(parts[0]))
            if header.get("alg") != "HS256":
                return IGNORE, None
            signing = f"{parts[0]}.{parts[1]}".encode()
            sig = hmac.new(self.secret, signing, hashlib.sha256).digest()
            if not hmac.compare_digest(sig, self._b64d(parts[2])):
                return DENY, pkt.RC_BAD_USERNAME_OR_PASSWORD
            claims = json.loads(self._b64d(parts[1]))
        except Exception:
            return DENY, pkt.RC_BAD_USERNAME_OR_PASSWORD
        if "exp" in claims and time.time() > claims["exp"]:
            return DENY, pkt.RC_BAD_USERNAME_OR_PASSWORD
        for claim, expect in self.verify_claims.items():
            expect = expect.replace(
                "${clientid}", client_info.get("client_id", "")
            ).replace("${username}", client_info.get("username") or "")
            if claims.get(claim) != expect:
                return DENY, pkt.RC_NOT_AUTHORIZED
        client_info["jwt_claims"] = claims
        return OK, None

    @classmethod
    def sign(cls, secret: bytes, claims: Dict) -> str:
        """Test/tooling helper: mint an HS256 token."""

        def b64(b: bytes) -> str:
            return base64.urlsafe_b64encode(b).rstrip(b"=").decode()

        h = b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
        p = b64(json.dumps(claims).encode())
        sig = hmac.new(secret, f"{h}.{p}".encode(), hashlib.sha256).digest()
        return f"{h}.{p}.{b64(sig)}"


class AuthChain:
    """Ordered providers; 'ignore' falls through, default allow when no
    provider claims the client (reference behavior with an empty chain)."""

    def __init__(self, providers: Optional[List[Provider]] = None, allow_anonymous: bool = True):
        self.providers = providers or []
        self.allow_anonymous = allow_anonymous

    def authenticate(self, client_info, credentials, acc=None):
        if credentials.get("enhanced_auth"):
            # already vouched by the enhanced-auth exchange (SCRAM); the
            # ban gate runs at higher priority on the same hookpoint
            return None
        for p in self.providers:
            result, rc = p.authenticate(client_info, credentials)
            d = self._decide(result, rc)
            if d is not None:
                return d
        return self._fallthrough()

    async def aauthenticate(self, client_info, credentials, acc=None):
        """The hook-registered path (channel runs auth via arun_fold, so a
        slow HTTP/JWKS backend suspends only that client's task)."""
        if credentials.get("enhanced_auth"):
            return None
        for p in self.providers:
            result, rc = await p.authenticate_async(client_info, credentials)
            d = self._decide(result, rc)
            if d is not None:
                return d
        return self._fallthrough()

    @staticmethod
    def _decide(result, rc):
        if result == OK:
            return ("stop", {"result": "allow"})
        if result == DENY:
            return (
                "stop",
                {"result": "deny", "reason_code": rc or pkt.RC_NOT_AUTHORIZED},
            )
        return None

    def _fallthrough(self):
        if not self.allow_anonymous:
            # no provider vouched for the client: deny (even with an empty
            # provider list — enabling auth without users must not be open)
            return (
                "stop",
                {"result": "deny", "reason_code": pkt.RC_NOT_AUTHORIZED},
            )
        return None  # no opinion

    def attach(self, hooks: Hooks) -> None:
        hooks.add("client.authenticate", self.aauthenticate, priority=100)
