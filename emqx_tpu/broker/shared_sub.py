"""Shared subscriptions: $share/<group>/<topic> load-balanced dispatch.

Parity with the reference (apps/emqx/src/emqx_shared_sub.erl:61-66
strategies, :234-285 pick logic): strategies random | round_robin | sticky |
hash_clientid | hash_topic, group membership registry, and one-of-N dispatch
per message per group. The reference's per-message ACK/NACK redispatch
(:118-130) maps to `dispatch` retrying the remaining members when a
deliverer raises.

A single real topic filter can carry several groups plus plain subscribers;
the broker routes the REAL filter and calls `dispatch_groups` alongside
normal fan-out.
"""

from __future__ import annotations

import random as _random
from typing import Dict, List, Optional, Tuple

from emqx_tpu.utils.tracepoints import tp


def stable_hash(s: Optional[str]) -> int:
    """FNV-1a 32-bit over the utf-8 bytes. Deterministic across runs and
    identical to the device-side pick input, unlike Python's randomized
    ``hash()`` (the reference uses erlang:phash2 the same way,
    emqx_shared_sub.erl:234-285)."""
    h = 0x811C9DC5
    for b in (s or "").encode("utf-8", "surrogatepass"):
        h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
    return h


class _Group:
    __slots__ = ("members", "rr_index", "sticky_sid")

    def __init__(self) -> None:
        self.members: Dict[str, object] = {}  # sid -> Subscriber
        self.rr_index = 0
        self.sticky_sid: Optional[str] = None


class SharedSub:
    def __init__(self, strategy: str = "round_robin"):
        self.strategy = strategy
        # real_filter -> {group -> _Group}
        self._table: Dict[str, Dict[str, _Group]] = {}
        self._rng = _random.Random(0xEC0)
        # cluster mode: (real, group, msg) -> bool; exactly one member
        # node dispatches each message. Every member node already holds
        # the message (route forwarding), so rotating the dispatcher
        # per message balances the group cluster-wide with zero extra
        # RPC (the reference picks among cluster-wide members,
        # emqx_shared_sub.erl:234-285)
        self.leader_check = None

    def _is_leader(self, real: str, group: str, msg=None) -> bool:
        lc = self.leader_check
        return True if lc is None else lc(real, group, msg)

    # -- membership -------------------------------------------------------
    def subscribe(self, group: str, real: str, sub) -> bool:
        groups = self._table.setdefault(real, {})
        g = groups.get(group)
        created = False
        if g is None:
            g = groups[group] = _Group()
            created = True
        g.members[sub.sid] = sub
        return created

    def unsubscribe(self, group: str, real: str, sid: str) -> Tuple[bool, bool]:
        """-> (removed, group_now_empty)"""
        groups = self._table.get(real)
        if not groups or group not in groups:
            return False, False
        g = groups[group]
        removed = g.members.pop(sid, None) is not None
        if g.sticky_sid == sid:
            g.sticky_sid = None
        empty = not g.members
        if empty:
            del groups[group]
            if not groups:
                del self._table[real]
        return removed, empty

    def count(self) -> int:
        return sum(
            len(g.members)
            for groups in self._table.values()
            for g in groups.values()
        )

    def subscriptions(self) -> List[Tuple[str, str, object]]:
        out = []
        for real, groups in self._table.items():
            for gname, g in groups.items():
                for sub in g.members.values():
                    out.append(
                        (sub.client_id, f"$share/{gname}/{real}", sub.opts)
                    )
        return out

    def subscriptions_sids(self) -> List[Tuple[str, str]]:
        """(sid, original $share filter) pairs — worker-fabric cleanup."""
        out = []
        for real, groups in self._table.items():
            for gname, g in groups.items():
                for sid in g.members:
                    out.append((sid, f"$share/{gname}/{real}"))
        return out

    def route_filter(self, group: str, real: str) -> str:
        """The filter registered in the route table for a shared sub."""
        return real

    # -- dispatch ---------------------------------------------------------
    def _pick(self, g: _Group, msg) -> List[str]:
        """Ordered candidate sids: first is the pick, rest are failover."""
        sids = list(g.members.keys())
        if not sids:
            return []
        s = self.strategy
        if s == "random":
            self._rng.shuffle(sids)
            return sids
        if s == "sticky":
            if g.sticky_sid in g.members:
                first = g.sticky_sid
            else:
                first = self._rng.choice(sids)
                g.sticky_sid = first
            rest = [x for x in sids if x != first]
            return [first] + rest
        if s == "hash_clientid":
            i = stable_hash(msg.from_client) % len(sids)
        elif s == "hash_topic":
            i = stable_hash(msg.topic) % len(sids)
        else:  # round_robin
            i = g.rr_index % len(sids)
            g.rr_index += 1
        return sids[i:] + sids[:i]

    # -- device-pick delivery (the host half of SURVEY hard part (d)) ------
    def group(self, real: str, gname: str) -> Optional[_Group]:
        groups = self._table.get(real)
        return groups.get(gname) if groups else None

    def dispatch_picked(self, real: str, gname: str, idx: int, msg) -> int:
        """Deliver to the device-picked member index, host keeping only
        ack/retry failover (emqx_shared_sub.erl:165-189 redispatch). The
        pick came from a table snapshot, so an out-of-range idx (members
        left since) just means failover order starts elsewhere."""
        g = self.group(real, gname)
        if g is None or not g.members:
            return 0
        if not self._is_leader(real, gname, msg):
            return 0  # another node's members own this message's pick
        sids = list(g.members.keys())
        i = idx % len(sids) if sids else 0
        candidates = sids[i:] + sids[:i]
        for sid in candidates:
            sub = g.members.get(sid)
            if sub is None:
                continue
            try:
                sub.deliver(msg, sub.opts)
                tp("shared.delivered", sid=sid, mid=str(msg.mid))
                if self.strategy == "sticky":
                    g.sticky_sid = sid
                elif self.strategy == "round_robin":
                    g.rr_index += 1
                return 1
            except Exception:
                tp("shared.nack", sid=sid, mid=str(msg.mid))
                continue
        return 0

    def dispatch_groups(self, real: str, msg) -> int:
        """Deliver to ONE member of each group subscribed at `real`.

        A deliverer raising is the NACK analog: the next candidate is tried
        (emqx_shared_sub redispatch, emqx_shared_sub.erl:165-189).
        """
        groups = self._table.get(real)
        if not groups:
            return 0
        n = 0
        for gname, g in groups.items():
            if not self._is_leader(real, gname, msg):
                continue  # another node's members own this message's pick
            for sid in self._pick(g, msg):
                sub = g.members.get(sid)
                if sub is None:
                    continue
                try:
                    sub.deliver(msg, sub.opts)
                    tp("shared.delivered", sid=sid, mid=str(msg.mid))
                    n += 1
                    break
                except Exception:
                    tp("shared.nack", sid=sid, mid=str(msg.mid))
                    continue  # NACK -> failover to next member
        return n

    def has_groups(self, real: str) -> bool:
        return real in self._table
