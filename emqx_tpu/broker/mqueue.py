"""Bounded priority message queue (reference: apps/emqx/src/emqx_mqueue.erl).

Per-topic priorities, bounded length, drop policy; $SYS-topic messages can
be dropped preferentially like the reference's `store_qos0`/priorities
behavior. QoS0 messages may bypass the queue entirely when the inflight
window has room (handled by the session)."""

from __future__ import annotations

from collections import deque
from typing import Dict, Optional

from emqx_tpu.broker.message import Message


class MQueue:
    def __init__(
        self,
        max_len: int = 1000,
        priorities: Optional[Dict[str, int]] = None,
        default_priority: int = 0,
        store_qos0: bool = True,
    ):
        self.max_len = max_len
        self.priorities = priorities or {}
        self.default_priority = default_priority
        self.store_qos0 = store_qos0
        # priority -> deque; drained highest priority first
        self._qs: Dict[int, deque] = {}
        self._len = 0
        self.dropped = 0

    def __len__(self) -> int:
        return self._len

    def _prio(self, msg: Message) -> int:
        return self.priorities.get(msg.topic, self.default_priority)

    def in_(self, msg: Message) -> Optional[Message]:
        """Enqueue; returns a dropped message if the queue was full."""
        if msg.qos == 0 and not self.store_qos0:
            self.dropped += 1
            return msg
        # slab-escape site: banked messages outlive their fabric frame —
        # materialize before queueing (no-op for ordinary messages)
        msg.own_buffers()
        p = self._prio(msg)
        q = self._qs.setdefault(p, deque())
        dropped = None
        if self.max_len and self._len >= self.max_len:
            # drop-oldest within the lowest priority band
            lowest = min(self._qs, key=lambda k: (k, ))
            lq = self._qs[lowest]
            if lq:
                dropped = lq.popleft()
                self._len -= 1
                self.dropped += 1
        q.append(msg)
        self._len += 1
        return dropped

    def out(self) -> Optional[Message]:
        if self._len == 0:
            return None
        for p in sorted(self._qs, reverse=True):
            q = self._qs[p]
            if q:
                self._len -= 1
                return q.popleft()
        return None

    def peek_all(self):
        for p in sorted(self._qs, reverse=True):
            yield from self._qs[p]
