"""Retained-replay storm feed: wildcard SUBSCRIBEs ride the serving launch.

A wildcard SUBSCRIBE against a big retained store used to pay its own
launch+readback train (one `_retained_step` launch per stored chunk,
models/retained_index.py) — per subscriber, on the hook path. This feed
turns a subscribe storm into ONE device pass that rides the publish
pipeline:

- concurrent replay requests aggregate here (the subscribe-side analog
  of `BatchIngest`'s publish window);
- when the broker launches a device batch (`Broker.adispatch_begin`),
  it calls `take_job()` and the pending filters fuse into that launch
  (`fused_route_retained_step`): zero extra launches, zero extra
  readbacks for single-chunk stores;
- when no publish launch shows up inside the window (quiet broker, pure
  subscribe storm), the flush timer answers every pending filter with
  one standalone `match_many` pass on the dispatch executor — still one
  launch train for the WHOLE storm instead of one per subscriber.

Waiters receive the matched retained TOPICS (already row-resolved); the
Retainer re-fetches each message from its authoritative store, so a
stale row (topic deleted while the storm was in flight) costs a lookup,
never a wrong replay.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, List, Optional

from emqx_tpu.observe import faults as _faults
from emqx_tpu.utils.tracepoints import tp

log = logging.getLogger("emqx_tpu.retained_feed")


class RetainedStormFeed:
    # the feed is LOW-priority work by construction: a retained replay
    # is best-effort catch-up traffic, so under SLO backpressure it
    # defers behind live control/normal publishes (broker/slo.py)
    LANE = "low"

    def __init__(self, retained_index, metrics=None, window_s: float = 0.002):
        self.index = retained_index
        self.metrics = metrics
        self.window_s = window_s
        # SloController (broker/slo.py), attached by the app: on the
        # `defer` rung and above, pending storms sit launches out (and
        # the standalone flush re-arms) until the defer age bound —
        # a replay flood never deepens an already-violating tail
        self.slo = None
        # filter -> [futures]; multiple subscribers to the same filter
        # share one lane in the storm's shape table
        self._pending: Dict[str, List[asyncio.Future]] = {}
        self._oldest_t: Optional[float] = None  # first pending submit
        self._waiters: Dict[int, Dict] = {}  # id(job) -> waiters
        self._timer = None
        self._flushing = False  # a standalone match_many pass in flight

    def head_age(self, now: Optional[float] = None) -> float:
        """Seconds the OLDEST pending replay has waited (0 when none) —
        the anti-starvation input to the SLO defer gate."""
        if self._oldest_t is None:
            return 0.0
        return (time.monotonic() if now is None else now) - self._oldest_t

    def _deferred(self) -> bool:
        return self.slo is not None and self.slo.defer_low(self.head_age())

    def __len__(self) -> int:
        return len(self._pending)

    # -- subscribe side ----------------------------------------------------
    def submit(self, filter_: str) -> asyncio.Future:
        """Queue one replay; resolves with the matched retained topic
        list (or an exception — callers fall back to the CPU walk)."""
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        if not self._pending:
            self._oldest_t = time.monotonic()
        self._pending.setdefault(filter_, []).append(fut)
        if self.metrics is not None:
            self.metrics.inc("retained.storm.filters")
        if self._timer is None:
            self._timer = loop.call_later(self.window_s, self._on_window)
        return fut

    # -- serving-pipeline side --------------------------------------------
    def take_job(self):
        """Called by the broker on the loop thread right before a device
        launch: pops every pending filter into a prepared StormJob the
        launch fuses in, or returns None (nothing pending / index not
        fusable / a standalone flush already owns the pending set)."""
        if not self._pending or self._flushing:
            return None
        if self._deferred():
            # SLO `defer` rung: the replay storm is low-priority — let
            # THIS launch carry only live traffic; the storm rides a
            # later one (or the age bound forces it through)
            if self.metrics is not None:
                self.metrics.inc("retained.storm.deferred")
            return None
        filters = list(self._pending)
        job = None
        try:
            # fault site: a failed storm prepare exercises exactly this
            # except-arm (every waiter falls back to the CPU walk)
            _faults.hit("retained.storm")
            job = self.index.prepare_storm(filters)
        except Exception:  # noqa: BLE001 — never poison the launch
            log.exception("storm prepare failed; falling back to CPU")
        if job is None:
            # not fusable (empty index / over-budget filter): answer the
            # waiters with a CPU-fallback signal now
            waiters, self._pending = self._pending, {}
            self._oldest_t = None
            self._cancel_timer()
            for futs in waiters.values():
                for f in futs:
                    if not f.done():
                        f.set_result(None)
            return None
        waiters, self._pending = self._pending, {}
        self._oldest_t = None
        self._cancel_timer()
        self._waiters[id(job)] = waiters
        if self.metrics is not None:
            self.metrics.inc("retained.storm.fused")
        tp("retained.storm.fused", filters=len(filters))
        return job

    def attach(self, job, fut) -> None:
        """Fail the storm's waiters if the fused launch itself dies —
        `resolve` only runs when the batch settles successfully."""

        def _done(f):
            exc = f.exception() if not f.cancelled() else None
            if exc is not None or f.cancelled():
                self.fail(job, exc)

        fut.add_done_callback(_done)

    def resolve(self, job, matched: Optional[Dict]) -> None:
        """Hand decoded {filter: row-index array} to the waiters (loop
        thread, at batch settle). Rows materialize to topics here — the
        index's row table is loop-thread state."""
        waiters = self._waiters.pop(id(job), None)
        if waiters is None:
            return
        for f, futs in waiters.items():
            rows = matched.get(f) if matched is not None else None
            if rows is None:
                topics = None  # CPU-fallback signal
            else:
                topics = [
                    t
                    for t in (self.index.topic_at(int(r)) for r in rows)
                    if t is not None
                ]
            for fut in futs:
                if not fut.done():
                    fut.set_result(topics)

    def fail(self, job, exc) -> None:
        waiters = self._waiters.pop(id(job), None)
        if waiters is None:
            return
        for futs in waiters.values():
            for fut in futs:
                if not fut.done():
                    # None = "fall back to the CPU walk" — a failed
                    # device launch must not fail the SUBSCRIBE replay
                    fut.set_result(None)
        if exc is not None:
            log.warning("fused retained storm failed: %r", exc)

    # -- standalone flush --------------------------------------------------
    def _cancel_timer(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def _on_window(self) -> None:
        self._timer = None
        if not self._pending or self._flushing:
            return
        if self._deferred():
            # deferred: re-arm instead of flushing — the standalone pass
            # costs a launch train exactly when the ladder says the
            # pipeline can't afford one. head_age bounds the wait.
            if self.metrics is not None:
                self.metrics.inc("retained.storm.deferred")
            self._timer = asyncio.get_running_loop().call_later(
                self.window_s, self._on_window
            )
            return
        asyncio.ensure_future(self._flush())

    async def _flush(self) -> None:
        """No publish launch took the storm inside the window: answer it
        with one standalone match_many pass (still ONE launch train for
        the whole storm). `_flushing` parks take_job so the pending set
        and the chunk uploads have exactly one owner."""
        from emqx_tpu.broker.broker import dispatch_pool

        self._flushing = True
        try:
            waiters, self._pending = self._pending, {}
            self._oldest_t = None
            filters = list(waiters)
            if self.metrics is not None:
                self.metrics.inc("retained.storm.flushed")
            tp("retained.storm.flushed", filters=len(filters))
            loop = asyncio.get_running_loop()
            try:
                matched = await loop.run_in_executor(
                    dispatch_pool(), self.index.match_many, filters
                )
            except Exception:  # noqa: BLE001 — replay must not hang
                log.exception("standalone storm flush failed")
                matched = None
            for f, futs in waiters.items():
                if matched is None:
                    topics = None
                else:
                    topics = [
                        t
                        for t in (
                            self.index.topic_at(int(r))
                            for r in matched.get(f, ())
                        )
                        if t is not None
                    ]
                for fut in futs:
                    if not fut.done():
                        fut.set_result(topics)
        finally:
            self._flushing = False
            if self._pending and self._timer is None:
                self._timer = asyncio.get_running_loop().call_later(
                    self.window_s, self._on_window
                )
