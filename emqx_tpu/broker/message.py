"""Internal message record (reference: apps/emqx/src/emqx_message.erl #message{}).

Carries GUID id, qos, origin, flags, headers (extension scratch), topic,
payload, and creation/expiry timestamps.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from emqx_tpu.utils.guid import next_guid


@dataclass
class Message:
    topic: str
    payload: bytes = b""
    qos: int = 0
    retain: bool = False
    dup: bool = False
    from_client: str = ""
    from_username: Optional[str] = None
    mid: int = field(default_factory=next_guid)
    headers: Dict = field(default_factory=dict)
    properties: Dict = field(default_factory=dict)
    timestamp: float = field(default_factory=time.time)

    def is_expired(self, now: Optional[float] = None) -> bool:
        exp = self.properties.get("Message-Expiry-Interval")
        if exp is None:
            return False
        return (now or time.time()) > self.timestamp + exp

    def is_sys(self) -> bool:
        return self.topic.startswith("$SYS/")
