"""Internal message record (reference: apps/emqx/src/emqx_message.erl #message{}).

Carries GUID id, qos, origin, flags, headers (extension scratch), topic,
payload, and creation/expiry timestamps.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Optional  # noqa: F401 — Dict used by SlabMessage

from emqx_tpu.utils.guid import next_guid


@dataclass
class Message:
    topic: str
    payload: bytes = b""
    qos: int = 0
    retain: bool = False
    dup: bool = False
    from_client: str = ""
    from_username: Optional[str] = None
    mid: int = field(default_factory=next_guid)
    headers: Dict = field(default_factory=dict)
    properties: Dict = field(default_factory=dict)
    timestamp: float = field(default_factory=time.time)

    def is_expired(self, now: Optional[float] = None) -> bool:
        exp = self.properties.get("Message-Expiry-Interval")
        if exp is None:
            return False
        return (now or time.time()) > self.timestamp + exp

    def is_sys(self) -> bool:
        return self.topic.startswith("$SYS/")

    # -- zero-copy surface (overridden by SlabMessage) --------------------
    def topic_bytes(self):
        """Topic as bytes-like, without forcing a fresh decode cycle."""
        return self.topic.encode("utf-8", "surrogatepass")

    def topic_key(self):
        """Tokenizer input: str here; a `TopicRef` into the fabric read
        slab for un-materialized SlabMessages (ops/tokenizer slab path)."""
        return self.topic

    def payload_view(self):
        """Payload as a bytes-like view (no copy for slab messages)."""
        return self.payload or b""

    def own_buffers(self) -> "Message":
        """Ownership discipline (docs/protocol_plane.md): a message about
        to outlive its dispatch (retained store, queued/banked, session
        slab, parked fabric delivery) must own its bytes — no memoryview
        into a fabric read buffer may escape past buffer recycle. No-op
        here; SlabMessage materializes and drops the slab reference."""
        return self


class SlabMessage(Message):
    """A Message whose topic/payload still live inside a fabric read
    slab (`transport/fabric.PubSlab`/`DlvSlab`): str decode and payload
    copies are deferred until a consumer actually needs them — the
    zero-copy ingest seam (the router feeds `topic_key()` straight into
    the tokenizer's topic matrix with one vectorized gather per slab).

    Lifetime: the slab reference pins the WHOLE frame body, so every
    long-lived store must call `own_buffers()` first (annotated escape
    sites: retainer insert, mqueue banking, session-store slab, fabric
    parking). Pickle/copy materialize automatically."""

    def __init__(self, slab, i: int, qos: int = 0, retain: bool = False,
                 dup: bool = False, from_client: str = "",
                 properties: Optional[Dict] = None):
        # deliberate bypass of the dataclass __init__: topic/payload are
        # lazy properties backed by (slab, i)
        self._slab = slab
        self._i = i
        self._topic: Optional[str] = None
        self._payload: Optional[bytes] = None
        self.qos = qos
        self.retain = retain
        self.dup = dup
        self.from_client = from_client
        self.from_username = None
        self.mid = next_guid()
        self.headers = {}
        self.properties = properties if properties is not None else {}
        self.timestamp = time.time()

    @property
    def topic(self) -> str:  # type: ignore[override]
        t = self._topic
        if t is None:
            t = self._topic = str(
                self._slab.topic_bytes(self._i), "utf-8"
            )
        return t

    @topic.setter
    def topic(self, v: str) -> None:
        self._topic = v

    @property
    def payload(self) -> bytes:  # type: ignore[override]
        p = self._payload
        if p is None:
            p = self._payload = bytes(self._slab.payload_view(self._i))
        return p

    @payload.setter
    def payload(self, v: bytes) -> None:
        self._payload = v

    def topic_bytes(self):
        if self._slab is not None and self._topic is None:
            return self._slab.topic_bytes(self._i)
        return self.topic.encode("utf-8", "surrogatepass")

    def is_sys(self) -> bool:
        # lane classification (broker/ingest.py lane_of) runs on every
        # enqueue: answer from the slab view, never force a str decode
        if self._slab is not None and self._topic is None:
            tb = self._slab.topic_bytes(self._i)
            return bytes(tb[:5]) == b"$SYS/"
        return self.topic.startswith("$SYS/")

    def topic_key(self):
        if self._slab is not None and self._topic is None:
            from emqx_tpu.ops.tokenizer import TopicRef

            s = self._slab
            return TopicRef(
                s.flat, int(s.t_off[self._i]), int(s.t_len[self._i])
            )
        return self.topic

    def payload_view(self):
        if self._slab is not None and self._payload is None:
            return self._slab.payload_view(self._i)
        return self._payload or b""

    def own_buffers(self) -> "Message":
        if self._slab is not None:
            if self._topic is None:
                self._topic = str(self._slab.topic_bytes(self._i), "utf-8")
            if self._payload is None:
                self._payload = bytes(self._slab.payload_view(self._i))
            self._slab = None
            self._i = -1
        return self

    def __getstate__(self):
        # pickle (cluster forward) and copy.copy both route here: the
        # clone owns its bytes, never a view into the shared read slab
        self.own_buffers()
        return dict(self.__dict__)
