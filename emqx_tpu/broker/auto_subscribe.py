"""Server-side forced subscriptions on connect
(reference: apps/emqx_auto_subscribe, SURVEY.md §2.2: topics with
${clientid}/${username} placeholders subscribed for every new connection).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.mqtt import packet as pkt


@dataclass
class AutoSubscribeTopic:
    filter: str
    qos: int = 0
    no_local: bool = False
    retain_as_published: bool = False
    retain_handling: int = 0


class AutoSubscribe:
    def __init__(self, topics: List[AutoSubscribeTopic]):
        self.topics = topics

    def on_connected(self, ci, channel=None) -> None:
        if channel is None or channel.session is None:
            return
        for t in self.topics:
            f = t.filter.replace("${clientid}", ci.get("client_id", ""))
            f = f.replace("${username}", ci.get("username") or "")
            opts = pkt.SubOpts(
                qos=t.qos,
                no_local=t.no_local,
                retain_as_published=t.retain_as_published,
                retain_handling=t.retain_handling,
            )
            channel.broker.subscribe(
                channel.client_id,
                channel.client_id,
                f,
                opts,
                channel._make_deliverer(opts),
            )
            channel.session.subscriptions[f] = opts
            channel.hooks.run(
                "session.subscribed", ci, f, opts, channel
            )

    def attach(self, hooks: Hooks) -> None:
        hooks.add(
            "client.connected",
            lambda ci, channel=None: self.on_connected(ci, channel),
            priority=50,
        )
