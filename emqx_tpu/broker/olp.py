"""Overload protection (reference: apps/emqx/src/emqx_olp.erl + the `lc`
dependency's load_ctl, SURVEY.md §2.1).

The reference gates expensive work on `load_ctl:is_overloaded()` (BEAM
runqueue pressure) and backs off GC/hibernation/new connections. The
asyncio analog of runqueue pressure is event-loop lag: a sampler task
measures how late its own timer fires; sustained lag above the watermark
flips `is_overloaded()`, and the listener refuses new connections while it
holds (priority_connection semantics). The ingest gate additionally sheds
enqueues while overloaded (broker/ingest.py, docs/robustness.md).

The sampler is supervised: a raising sampler task restarts (with its
exception logged) instead of silently dying and leaving the broker
permanently blind to overload — `asyncio.ensure_future` alone would
swallow the traceback into a never-awaited task.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

log = logging.getLogger("emqx_tpu.olp")


class Olp:
    def __init__(
        self,
        enable: bool = True,
        lag_watermark_ms: float = 500.0,
        sample_interval: float = 0.1,
        cooldown: float = 5.0,
        metrics=None,
    ):
        self.enable = enable
        self.lag_watermark_ms = lag_watermark_ms
        self.sample_interval = sample_interval
        self.cooldown = cooldown
        self.metrics = metrics
        self.last_lag_ms = 0.0
        self._overloaded_until = 0.0
        self._task: Optional[asyncio.Task] = None
        self._stopping = False
        # stats for $SYS / REST
        self.trip_count = 0

    def is_overloaded(self) -> bool:
        return self.enable and time.monotonic() < self._overloaded_until

    def pressure(self) -> float:
        """Graded overload signal: last sampled loop lag as a fraction
        of the watermark (1.0 = at the trip point). The SLO controller
        and the hotpath REST read this — `is_overloaded()` is the binary
        trip, this is the dial behind it."""
        if not self.enable or self.lag_watermark_ms <= 0:
            return 0.0
        return self.last_lag_ms / self.lag_watermark_ms

    def note_lag(self, lag_ms: float) -> None:
        self.last_lag_ms = lag_ms
        if self.metrics is not None:
            self.metrics.gauge_set("olp.lag_ms", lag_ms)
        if lag_ms > self.lag_watermark_ms:
            if not self.is_overloaded():
                self.trip_count += 1
                if self.metrics is not None:
                    self.metrics.inc("olp.trips")
            self._overloaded_until = time.monotonic() + self.cooldown

    async def _sampler(self) -> None:
        while True:
            t0 = time.monotonic()
            await asyncio.sleep(self.sample_interval)
            lag_ms = (time.monotonic() - t0 - self.sample_interval) * 1000.0
            self.note_lag(max(0.0, lag_ms))

    def _spawn(self) -> None:
        self._task = asyncio.ensure_future(self._sampler())
        self._task.add_done_callback(self._on_sampler_done)

    def _on_sampler_done(self, task: asyncio.Task) -> None:
        """The sampler must outlive its own bugs: a task that died to an
        exception logs it and respawns; cancellation (stop()) does not."""
        if task.cancelled() or self._stopping:
            return
        exc = task.exception()
        if exc is None:
            return  # _sampler never returns normally; defensive
        log.error("olp sampler died: %r; restarting", exc)
        self._task = None
        try:
            self._spawn()
        except RuntimeError:
            # loop already closed (shutdown race): stay down
            self._task = None

    def start(self) -> None:
        if self.enable and self._task is None:
            self._stopping = False
            self._spawn()

    async def stop(self) -> None:
        self._stopping = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
