"""Broker half of the semantic routing plane (docs/semantic_routing.md).

`SemanticRouting` owns the `SemanticTable` (ops/semantic_table.py) and
everything host-side around it:

- **intake**: embedding filters arrive on SUBSCRIBE as MQTT5 user
  properties (``semantic-embedding`` = JSON float list or base64 f32le,
  optional ``semantic-threshold``) or through
  ``POST /api/v5/semantic/filters`` (mgmt/api.py); per-message query
  embeddings ride PUBLISH user properties the same way, with
  ``msg.headers["semantic_embedding"]`` as the copy-free internal path
  (bench drivers, bridges);
- **binding**: an entry binds to the subscription's fan-out SLOT
  (`Broker._slot_subs`) and optionally its topic-filter fid — semantic
  hits come back from the device as ordinary slot recipients, so
  dispatch needs zero new fan-out machinery;
- **host twin** (`host_route`): the authoritative numpy evaluator —
  the degrade target for CPU-fallback batches and single-message
  paths, and the reference the differential tests (and the
  `semantic_vs_host_filter_x` bench headline) compare against.

Delivery semantics: a subscription WITH an embedding filter delivers
when its topic scope matches AND similarity clears the threshold
(it is NOT in the plain subscriber table); an unscoped filter (REST,
or a ``#`` subscribe) delivers on similarity alone. Fan-out per
message is bounded by top-k BY DESIGN — "route to the k most similar
subscribers" — on a mesh the pick is per 'tp' shard (a bounded
superset: at most topk x tp winners). Retained replay is NOT
semantically filtered (replay runs before any message embedding
exists); live routing is the plane's scope.
"""

from __future__ import annotations

import base64
import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from emqx_tpu.ops import topics as T
from emqx_tpu.ops.semantic_table import SemanticTable, normalize

# MQTT5 user-property keys (SUBSCRIBE and PUBLISH)
PROP_EMBEDDING = "semantic-embedding"
PROP_THRESHOLD = "semantic-threshold"
# internal fast path: a ready np/list embedding in the message headers
HDR_EMBEDDING = "semantic_embedding"


def decode_embedding(value, dim: int) -> np.ndarray:
    """Wire formats: JSON float list (starts with '[') or base64 of
    little-endian f32 bytes. Raises ValueError on anything else."""
    if isinstance(value, (list, tuple, np.ndarray)):
        return normalize(value, dim)
    if isinstance(value, bytes):
        value = value.decode("utf-8", "replace")
    v = value.strip()
    if v.startswith("["):
        return normalize(json.loads(v), dim)
    raw = base64.b64decode(v, validate=True)
    if len(raw) != dim * 4:
        raise ValueError(
            f"embedding payload is {len(raw)}B, expected {dim * 4}"
        )
    return normalize(np.frombuffer(raw, "<f4"), dim)


def _user_props(properties: Optional[Dict]) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for k, v in (properties or {}).get("User-Property", ()):
        out.setdefault(k, v)
    return out


class SemanticRouting:
    """Embedding-filter registry + host evaluator, attached to a Broker
    as ``broker.semantic`` (app.py wires it from `semantic.*` config)."""

    def __init__(self, dim: int = 64, topk: int = 16,
                 threshold: float = 0.75, dtype: str = "float32",
                 shards: int = 1, metrics=None):
        self.table = SemanticTable(
            dim=dim, topk=topk, shards=shards, dtype=dtype
        )
        self.default_threshold = float(threshold)
        self.metrics = metrics
        # slot -> (sid, scope filter name | None, threshold); the REST
        # listing and the host twin's scope checks read this
        self._by_slot: Dict[int, Tuple[str, Optional[str], float]] = {}

    def __len__(self) -> int:
        return len(self.table)

    # -- intake -------------------------------------------------------------
    def parse_subscribe(self, properties: Optional[Dict]):
        """SUBSCRIBE properties -> (vec, threshold) or None (no
        embedding filter requested). Raises ValueError on a malformed
        embedding — the channel maps it to an error reason code."""
        props = _user_props(properties)
        raw = props.get(PROP_EMBEDDING)
        if raw is None:
            return None
        vec = decode_embedding(raw, self.table.dim)
        th = props.get(PROP_THRESHOLD)
        return vec, (
            float(th) if th is not None else self.default_threshold
        )

    def embedding_of(self, msg) -> Optional[np.ndarray]:
        """Per-message query embedding: headers fast path first, then
        the PUBLISH user property. None = no embedding (the row rides a
        zero vector — matches nothing at any positive threshold)."""
        e = msg.headers.get(HDR_EMBEDDING)
        if e is None:
            raw = _user_props(msg.properties).get(PROP_EMBEDDING)
            if raw is None:
                return None
            try:
                e = decode_embedding(raw, self.table.dim)
            except (ValueError, TypeError):
                if self.metrics is not None:
                    self.metrics.inc("semantic.embed.rejected")
                return None
            msg.headers[HDR_EMBEDDING] = e  # decode once per message
            return e
        try:
            return normalize(e, self.table.dim)
        except ValueError:
            if self.metrics is not None:
                self.metrics.inc("semantic.embed.rejected")
            return None

    def embed_batch(self, msgs) -> Optional[np.ndarray]:
        """[B, D] f32 query matrix, or None when NO row carries an
        embedding (the semantic stage still runs — zero rows match
        nothing — but the host skips building the matrix)."""
        out = None
        for i, m in enumerate(msgs):
            e = self.embedding_of(m)
            if e is None:
                continue
            if out is None:
                out = np.zeros((len(msgs), self.table.dim), np.float32)
            out[i] = e
        return out

    # -- binding ------------------------------------------------------------
    def attach(self, sid: str, slot: int, vec, threshold: float,
               fid: int = -1, scope: Optional[str] = None) -> None:
        """Bind (or replace) the embedding filter on a subscriber slot.
        `fid`/`scope` carry the topic-filter binding (fid for the
        device mask, the filter NAME for the host twin's T.match)."""
        self.table.add(slot, vec, threshold, fid=fid)
        self._by_slot[slot] = (sid, scope, float(threshold))
        if self.metrics is not None:
            self.metrics.gauge_set("semantic.filters", len(self.table))

    def detach(self, slot: int) -> bool:
        ok = self.table.remove(slot)
        self._by_slot.pop(slot, None)
        if ok and self.metrics is not None:
            self.metrics.gauge_set("semantic.filters", len(self.table))
        return ok

    def entries(self) -> List[Dict]:
        """REST listing (GET /api/v5/semantic/filters)."""
        out = []
        for slot, fid, th in self.table.entries():
            sid, scope, _th = self._by_slot.get(slot, ("?", None, th))
            out.append({
                "slot": slot,
                "clientid": sid,
                "topic_filter": scope,
                "fid": fid,
                "threshold": th,
            })
        return out

    # -- host twin ----------------------------------------------------------
    def host_route(self, msgs) -> List[List[int]]:
        """Authoritative numpy evaluation: per-message qualifying slots,
        GLOBAL top-k by similarity (the single-device kernel's
        semantics). The degrade target for CPU-fallback batches and the
        differential reference for the fused path."""
        n = len(msgs)
        if not len(self.table):
            return [[] for _ in range(n)]
        vecs, slots, fids, ths = self.table.live_arrays()
        q = self.embed_batch(msgs)
        if q is None:
            if self.metrics is not None:
                self.metrics.inc("semantic.host.batches")
            return [[] for _ in range(n)]
        sims = q @ vecs.T  # [B, E]
        out: List[List[int]] = []
        k = self.table.topk
        for i, m in enumerate(msgs):
            ok = sims[i] >= ths
            if not ok.any():
                out.append([])
                continue
            idx = np.nonzero(ok)[0]
            topic = m.topic
            keep = []
            for j in idx:
                if fids[j] >= 0:
                    _sid, scope, _t = self._by_slot.get(
                        int(slots[j]), (None, None, 0.0)
                    )
                    if scope is None or not T.match(topic, scope):
                        continue
                keep.append(j)
            if len(keep) > k:
                keep = sorted(keep, key=lambda j: -sims[i][j])[:k]
            out.append([int(slots[j]) for j in keep])
        if self.metrics is not None:
            self.metrics.inc("semantic.host.batches")
            self.metrics.inc(
                "semantic.host.matches", sum(len(r) for r in out)
            )
        return out

    def status(self) -> Dict:
        out = self.table.status()
        out["default_threshold"] = self.default_threshold
        return out
