"""MQTT protocol state machine, transport-agnostic.

Parity with the reference's emqx_channel (apps/emqx/src/emqx_channel.erl):
CONNECT handshake with authentication hook (:303-380), publish pipeline with
authz + QoS1/2 acks (:567-666), SUBSCRIBE/UNSUBSCRIBE (:455-502), deliver ->
session -> outgoing (:806-939), takeover/kick (:1015+), will message, and
the client.*/session.*/message.* hookpoints along the way.

Sans-IO: the transport provides a `sink` with send_packet(p)/close(reason);
timers call `tick()`. The channel never touches sockets, so the same state
machine serves TCP, TLS, WebSocket and in-process tests.
"""

from __future__ import annotations

import asyncio
import inspect
import secrets
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.broker import mountpoint as MP
from emqx_tpu.broker.message import Message
from emqx_tpu.broker.session import Session, SessionConfig
from emqx_tpu.mqtt import packet as pkt
from emqx_tpu.mqtt.frame import serialize
from emqx_tpu.ops import topics as T
from emqx_tpu.utils.tracepoints import atp, tp


@dataclass
class MqttCaps:
    """Negotiable capability limits (reference: emqx_mqtt_caps.erl)."""

    max_packet_size: int = 1024 * 1024
    max_clientid_len: int = 65535
    max_topic_levels: int = 128
    max_qos_allowed: int = 2
    retain_available: bool = True
    wildcard_subscription: bool = True
    shared_subscription: bool = True
    max_topic_alias: int = 65535


@dataclass
class ChannelConfig:
    caps: MqttCaps = field(default_factory=MqttCaps)
    session: SessionConfig = field(default_factory=SessionConfig)
    idle_timeout: float = 15.0
    enable_stats: bool = True
    # per-listener topic namespace prefix, ${clientid}/${username}
    # placeholders resolved at CONNECT (emqx_mountpoint.erl parity)
    mountpoint: Optional[str] = None
    # MQTT5 enhanced authentication: Authentication-Method -> authenticator
    # (start/finish state machine, e.g. auth/scram.ScramAuthenticator);
    # reference: emqx_channel enhanced auth + emqx_authn SCRAM mechanism
    enhanced_auth: Dict[str, object] = field(default_factory=dict)


class Channel:
    def __init__(
        self,
        broker: Broker,
        cm,
        sink,
        conninfo: Optional[Dict] = None,
        config: Optional[ChannelConfig] = None,
    ):
        self.broker = broker
        self.cm = cm
        self.sink = sink
        self.hooks: Hooks = broker.hooks
        self.conninfo = conninfo or {}
        self.config = config or ChannelConfig()
        self.state = "idle"
        self._ea = None  # in-flight enhanced-auth exchange
        self.version = pkt.MQTT_V4
        self.client_id = ""
        self.username: Optional[str] = None
        self.keepalive = 0
        self.clean_start = True
        self.session: Optional[Session] = None
        self.will: Optional[pkt.Will] = None
        self.connected_at: Optional[float] = None
        self.disconnect_reason: Optional[str] = None
        self.topic_aliases: Dict[int, str] = {}  # inbound alias -> topic
        # attrs set by auth providers during CONNECT (is_superuser, claims);
        # must persist so later authorize checks see them
        self.auth_attrs: Dict = {}
        # resolved at CONNECT via MP.replvar (placeholders need clientid)
        self.mountpoint: Optional[str] = None
        # pipelined-publish ack queue (active-N analog,
        # emqx_connection.erl:125): entries settle strictly FIFO so acks
        # keep MQTT-4.6.0 ordering even when dispatches resolve out of band
        self._ack_queue: deque = deque()
        self._ack_task: Optional[asyncio.Task] = None
        self._ack_drained: Optional[asyncio.Event] = None
        # hot-path client_info snapshot (see _ci_snapshot)
        self._ci: Optional[Dict] = None

    # -- helpers ----------------------------------------------------------
    def _send(self, p) -> None:
        self.sink.send_packet(p)
        self.broker.metrics.inc("packets.sent")

    def _close(self, reason: str, rc: Optional[int] = None) -> None:
        if rc is not None and self.version == pkt.MQTT_V5 and self.state == "connected":
            self._send(pkt.Disconnect(reason_code=rc))
        self.disconnect_reason = reason
        self.sink.close(reason)

    def client_info(self) -> Dict:
        return {
            "client_id": self.client_id,
            "username": self.username,
            "proto_ver": self.version,
            "clean_start": self.clean_start,
            "keepalive": self.keepalive,
            "mountpoint": self.mountpoint,
            **self.conninfo,
            **self.auth_attrs,
        }

    def _ci_snapshot(self) -> Dict:
        """Read-only client_info for the per-message hot paths (deliver /
        publish-authorize hooks): building the dict fresh per delivery was
        one of the larger host-plane costs. Rebuilt whenever the identity
        attributes change (connect completion, re-auth)."""
        ci = self._ci
        if ci is None:
            ci = self._ci = self.client_info()
        return ci

    # -- inbound dispatch -------------------------------------------------
    async def handle_in(self, p) -> None:
        self.broker.metrics.inc("packets.received")
        t = p.type
        if self.state == "idle":
            if t != pkt.CONNECT:
                return self._close("protocol_error")
            return await self._in_connect(p)
        if self.state == "authenticating":
            # mid enhanced-auth exchange: only AUTH (continue) is legal
            if t != pkt.AUTH:
                return self._close("protocol_error", pkt.RC_PROTOCOL_ERROR)
            return await self._in_auth_continue(p)
        if t == pkt.CONNECT:  # duplicate CONNECT is a protocol error
            return self._close("protocol_error", pkt.RC_PROTOCOL_ERROR)
        if t == pkt.PUBLISH:
            return await self._in_publish(p)
        if t == pkt.PUBACK:
            acked, more = self.session.puback(p.packet_id)
            if acked is not None:
                self.hooks.run("message.acked", self._ci_snapshot(), acked)
                self._delivery_completed(acked)
            for q in more:
                self._send(q)
            return
        if t == pkt.PUBREC:
            if self.session.pubrec(p.packet_id):
                rel = pkt.PubAck(packet_id=p.packet_id)
                rel.type = pkt.PUBREL
                self._send(rel)
            else:
                rel = pkt.PubAck(
                    packet_id=p.packet_id,
                    reason_code=pkt.RC_PACKET_IDENTIFIER_NOT_FOUND,
                )
                rel.type = pkt.PUBREL
                self._send(rel)
            return
        if t == pkt.PUBREL:
            ok = self.session.release_rel(p.packet_id)
            comp = pkt.PubAck(
                packet_id=p.packet_id,
                reason_code=pkt.RC_SUCCESS
                if ok
                else pkt.RC_PACKET_IDENTIFIER_NOT_FOUND,
            )
            comp.type = pkt.PUBCOMP
            self._send(comp)
            return
        if t == pkt.PUBCOMP:
            completed, more = self.session.pubcomp(p.packet_id)
            if completed is not None:
                self.hooks.run("message.acked", self._ci_snapshot(), completed)
                self._delivery_completed(completed)
            for q in more:
                self._send(q)
            return
        if t == pkt.SUBSCRIBE:
            return await self._in_subscribe(p)
        if t == pkt.UNSUBSCRIBE:
            return await self._in_unsubscribe(p)
        if t == pkt.PINGREQ:
            return self._send(pkt.PingResp())
        if t == pkt.DISCONNECT:
            return self._in_disconnect(p)
        if t == pkt.AUTH:
            # MQTT5 re-authentication (spec 4.12.1): allowed when the
            # method is configured; otherwise protocol error
            return await self._in_reauth(p)
        self._close("unexpected_packet")

    async def _in_reauth(self, p) -> None:
        method = p.properties.get("Authentication-Method")
        authenticator = self.config.enhanced_auth.get(method or "")
        if authenticator is None:
            return self._close(
                "auth_not_supported", pkt.RC_BAD_AUTHENTICATION_METHOD
            )
        if p.reason_code == pkt.RC_REAUTHENTICATE:
            r = authenticator.start(
                p.properties.get("Authentication-Data", b"")
            )
            if r[0] != "continue":
                return self._close("reauth_failed", pkt.RC_NOT_AUTHORIZED)
            _, server_first, ea_state = r
            self._ea = (None, None, method, authenticator, ea_state)
            self._send(
                pkt.Auth(
                    reason_code=pkt.RC_CONTINUE_AUTHENTICATION,
                    properties={
                        "Authentication-Method": method,
                        "Authentication-Data": server_first,
                    },
                )
            )
            return
        if p.reason_code == pkt.RC_CONTINUE_AUTHENTICATION and self._ea:
            _, _, ea_method, authenticator, ea_state = self._ea
            if method != ea_method:
                return self._close(
                    "reauth_method_mismatch", pkt.RC_BAD_AUTHENTICATION_METHOD
                )
            r = authenticator.finish(
                ea_state, p.properties.get("Authentication-Data", b"")
            )
            self._ea = None
            if r[0] != "ok":
                return self._close("reauth_failed", pkt.RC_NOT_AUTHORIZED)
            _, server_final, attrs = r
            self.auth_attrs.update(
                {k: v for k, v in attrs.items() if k != "username"}
            )
            self._ci = None  # re-auth may change identity attributes
            self._send(
                pkt.Auth(
                    reason_code=pkt.RC_SUCCESS,
                    properties={
                        "Authentication-Method": method,
                        "Authentication-Data": server_final,
                    },
                )
            )
            return
        self._close("protocol_error", pkt.RC_PROTOCOL_ERROR)

    # -- CONNECT ----------------------------------------------------------
    async def _in_connect(self, p: pkt.Connect) -> None:
        self.version = p.proto_ver
        self.clean_start = p.clean_start
        self.keepalive = p.keepalive
        self.username = p.username
        self.will = p.will
        client_id = p.client_id
        assigned = None
        if not client_id:
            if not p.clean_start and self.version < pkt.MQTT_V5:
                return self._connack_error(pkt.RC_CLIENT_IDENTIFIER_NOT_VALID)
            client_id = assigned = "emqx_tpu_" + secrets.token_hex(8)
        if len(client_id) > self.config.caps.max_clientid_len:
            return self._connack_error(pkt.RC_CLIENT_IDENTIFIER_NOT_VALID)
        self.client_id = client_id

        # MQTT5 enhanced authentication (AUTH exchange before CONNACK,
        # e.g. SCRAM-SHA-256; emqx_channel enhanced auth parity)
        method = (
            p.properties.get("Authentication-Method")
            if self.version == pkt.MQTT_V5
            else None
        )
        if method is not None:
            authenticator = self.config.enhanced_auth.get(method)
            if authenticator is None:
                return self._connack_error(pkt.RC_BAD_AUTHENTICATION_METHOD)
            r = authenticator.start(
                p.properties.get("Authentication-Data", b"")
            )
            if r[0] != "continue":
                return self._connack_error(pkt.RC_NOT_AUTHORIZED)
            _, server_first, ea_state = r
            self._ea = (p, assigned, method, authenticator, ea_state)
            self.state = "authenticating"
            self._send(
                pkt.Auth(
                    reason_code=pkt.RC_CONTINUE_AUTHENTICATION,
                    properties={
                        "Authentication-Method": method,
                        "Authentication-Data": server_first,
                    },
                )
            )
            return
        await self._connect_continue(p, assigned)

    async def _in_auth_continue(self, p: pkt.Auth) -> None:
        stashed, assigned, method, authenticator, ea_state = self._ea
        if p.properties.get("Authentication-Method") != method:
            return self._connack_error(pkt.RC_BAD_AUTHENTICATION_METHOD)
        r = authenticator.finish(
            ea_state, p.properties.get("Authentication-Data", b"")
        )
        if r[0] != "ok":
            await self.hooks.arun(
                "client.connack", self.client_info(), "not_authorized"
            )
            return self._connack_error(pkt.RC_NOT_AUTHORIZED)
        _, server_final, attrs = r
        self._ea = None
        if attrs.get("username") and not self.username:
            self.username = attrs["username"]
        self.auth_attrs.update(
            {k: v for k, v in attrs.items() if k != "username"}
        )
        await self._connect_continue(
            stashed,
            assigned,
            enhanced=True,
            extra_props={
                "Authentication-Method": method,
                "Authentication-Data": server_final,
            },
        )

    async def _connect_continue(
        self, p: pkt.Connect, assigned, enhanced=False, extra_props=None
    ) -> None:
        await self.hooks.arun("client.connect", self.client_info(), p)
        # authenticate fold ALWAYS runs — after enhanced auth too, so the
        # banned/flapping gate (priority 1000) and exhook still apply; the
        # marker tells credential providers the client is already vouched
        creds = (
            {"enhanced_auth": True}
            if enhanced
            else {"password": p.password}
        )
        ci = self.client_info()
        base_keys = set(ci)
        auth = await self.hooks.arun_fold(
            "client.authenticate", (ci, creds), None
        )
        # nemesis site: the await window in which a concurrent same-
        # clientid CONNECT can kick this channel (_gone() guards below)
        await atp("channel.authenticated", cid=self.client_id)
        # keep provider-set attrs (is_superuser, jwt claims) for the
        # channel's lifetime — authorize checks read them every packet
        self.auth_attrs.update(
            {k: v for k, v in ci.items() if k not in base_keys}
        )
        if isinstance(auth, dict) and auth.get("result") == "deny":
            await self.hooks.arun(
                "client.connack", self.client_info(), "not_authorized"
            )
            return self._connack_error(
                auth.get("reason_code", pkt.RC_NOT_AUTHORIZED)
            )

        self.mountpoint = MP.replvar(
            self.config.mountpoint, self.client_info()
        )
        r = self.cm.open_session(self)
        if inspect.isawaitable(r):
            # worker-fabric CM: the open resolves at the router (one
            # round trip covers node-wide discard/takeover/resume)
            r = await r
            if self.state not in ("idle", "authenticating") or (
                self.sink is not None
                and getattr(self.sink, "_closing", False)
            ):
                return  # kicked while awaiting the router
        session, present = r
        self.session = session
        if self.version == pkt.MQTT_V5:
            # v5 default expiry is 0 unless the client asks otherwise
            session.config.expiry_interval = p.properties.get(
                "Session-Expiry-Interval", 0
            )
        elif self.clean_start:
            session.config.expiry_interval = 0
        self.state = "connected"
        self.connected_at = time.time()
        self._ci = None  # identity finalized: next hot-path use snapshots
        props: pkt.Properties = {}
        if self.version == pkt.MQTT_V5:
            if assigned:
                props["Assigned-Client-Identifier"] = assigned
            props["Shared-Subscription-Available"] = 1
            props["Wildcard-Subscription-Available"] = 1
            props["Retain-Available"] = int(self.config.caps.retain_available)
            if extra_props:
                props.update(extra_props)  # enhanced-auth server-final
        await self.hooks.arun("client.connack", self.client_info(), "success")
        if self._gone(session):
            return  # kicked during the awaited hook (takeover race)
        tp("channel.connack", cid=self.client_id, present=present)
        self._send(
            pkt.Connack(
                session_present=present,
                reason_code=pkt.RC_SUCCESS
                if self.version == pkt.MQTT_V5
                else pkt.CONNACK_ACCEPT,
                properties=props,
            )
        )
        await self.hooks.arun("client.connected", self.client_info(), self)
        if self._gone(session):
            return
        if present:
            for q in self.session.replay():
                self._send(q)

    def _gone(self, session) -> bool:
        """True when this channel lost its session while awaiting a hook
        (a concurrent same-clientid CONNECT kicked/takeover'd us — the
        awaits in the async pipeline reopened the window the reference
        closes with per-clientid global locks, emqx_cm.erl:245-273)."""
        return self.session is not session or self.state == "disconnected"

    def _connack_error(self, rc: int) -> None:
        from emqx_tpu.mqtt import reason_codes as RC

        code = rc if self.version == pkt.MQTT_V5 else pkt.connack_compat(rc)
        self._send(pkt.Connack(session_present=False, reason_code=code))
        # close reason carries the spec name (emqx_reason_codes:name/1),
        # which is what traces / client.disconnected hooks surface
        self._close(f"connack_{RC.name(rc)}")

    # -- PUBLISH ----------------------------------------------------------
    async def _in_publish(self, p: pkt.Publish) -> None:
        topic = p.topic
        # MQTT5 topic alias resolution (emqx_channel packet pipeline :567-576)
        alias = p.properties.get("Topic-Alias") if self.version == pkt.MQTT_V5 else None
        if alias is not None:
            if alias == 0 or alias > self.config.caps.max_topic_alias:
                return self._close("topic_alias_invalid", pkt.RC_TOPIC_ALIAS_INVALID)
            if topic:
                self.topic_aliases[alias] = topic
            else:
                topic = self.topic_aliases.get(alias)
                if topic is None:
                    return self._close(
                        "unknown_topic_alias", pkt.RC_PROTOCOL_ERROR
                    )
        try:
            T.validate(topic, kind="name")
        except T.TopicValidationError:
            return self._close("invalid_topic", pkt.RC_TOPIC_NAME_INVALID)
        if len(T.words(topic)) > self.config.caps.max_topic_levels:
            return self._close("too_many_levels", pkt.RC_TOPIC_NAME_INVALID)
        if p.qos > self.config.caps.max_qos_allowed:
            return self._close("qos_not_supported", pkt.RC_QOS_NOT_SUPPORTED)
        if p.retain and not self.config.caps.retain_available:
            return self._close("retain_disabled", pkt.RC_RETAIN_NOT_SUPPORTED)

        allowed = await self.hooks.arun_fold(
            "client.authorize", (self._ci_snapshot(), "publish", topic),
            "allow",
        )
        if allowed != "allow":
            self.broker.metrics.inc("messages.dropped.not_authorized")
            if allowed == "disconnect":
                # authz deny_action=disconnect (reference knob): drop the
                # packet and close the connection
                return self._close("not_authorized", pkt.RC_NOT_AUTHORIZED)
            if p.qos == 0:
                return  # silently drop (emqx default for qos0 deny)
            ack = pkt.PubAck(
                packet_id=p.packet_id, reason_code=pkt.RC_NOT_AUTHORIZED
            )
            ack.type = pkt.PUBACK if p.qos == 1 else pkt.PUBREC
            # through the ack queue: earlier pipelined publishes must ack first
            return self._enqueue_ack(0, lambda n: self._send(ack))

        if self.session is None or self.state != "connected":
            return  # kicked while awaiting the authorize hook
        msg = Message(
            topic=MP.mount(self.mountpoint, topic),
            payload=p.payload,
            qos=p.qos,
            retain=p.retain,
            from_client=self.client_id,
            from_username=self.username,
            properties={
                k: v for k, v in p.properties.items() if k != "Topic-Alias"
            },
        )
        if p.qos == 0:
            r = await self._publish_pipelined(msg)
            if not isinstance(r, int):
                self._enqueue_ack(r)
            return
        if p.qos == 1:
            r = await self._publish_pipelined(msg)
            pid = p.packet_id
            return self._enqueue_ack(
                r, lambda n: self._send_pub_ack(pid, n, pkt.PUBACK)
            )
        # QoS2: publish on first sight of the packet id, dedupe on DUP resend
        try:
            fresh = self.session.await_rel(p.packet_id)
        except OverflowError:
            return self._close("receive_max", pkt.RC_RECEIVE_MAXIMUM_EXCEEDED)
        pid = p.packet_id
        send_rec = lambda n: self._send_pub_ack(pid, n, pkt.PUBREC)  # noqa: E731
        if fresh:
            r = await self._publish_pipelined(msg)
            # on dispatch failure the dedup record must be rolled back, or
            # the client's retransmit would be "DUP"-acked without the
            # message ever publishing (silent QoS2 loss)
            sess = self.session
            self._enqueue_ack(
                r, send_rec, on_fail=lambda: sess.release_rel(pid)
            )
        else:
            self._enqueue_ack(-1, send_rec)  # dup: never no-subscribers rc

    # active-N analog (emqx_connection.erl:125 ?ACTIVE_N): how many
    # publishes one channel may have riding the batch window before the
    # read path stalls awaiting the oldest dispatch (backpressure)
    PUB_PIPELINE_MAX = 100

    async def _publish_pipelined(self, msg: Message):
        """Enqueue to the batch ingest without awaiting dispatch (returns a
        future). At the pipeline cap, stall the read path until the ack
        drainer catches up — ordering is preserved either way."""
        while len(self._ack_queue) >= self.PUB_PIPELINE_MAX:
            self._ack_drained = asyncio.Event()
            await self._ack_drained.wait()
        return await self.broker.apublish_enqueue(msg)

    def _send_pub_ack(self, packet_id: int, n: int, ack_type: int) -> None:
        rc = pkt.RC_SUCCESS
        if n == 0 and self.version == pkt.MQTT_V5:
            rc = pkt.RC_NO_MATCHING_SUBSCRIBERS
        ack = pkt.PubAck(packet_id=packet_id, reason_code=rc)
        ack.type = ack_type
        self._send(ack)

    def _enqueue_ack(self, r, send=None, on_fail=None) -> None:
        """Settle a publish through the FIFO ack queue.

        `r` is an int (already dispatched) or a future. `send(n)` emits the
        ack; `on_fail()` rolls back state if the dispatch errored. The fast
        path (resolved result, empty queue) acks inline; otherwise a single
        drainer task per channel settles entries strictly in order.
        """
        # inline fast path ONLY when nothing is pending anywhere: the
        # drainer holds its current entry OUTSIDE the queue while awaiting,
        # so an empty queue alone doesn't mean order-safe
        if (
            isinstance(r, int)
            and not self._ack_queue
            and (self._ack_task is None or self._ack_task.done())
        ):
            if send is not None:
                send(r)
            return
        self._ack_queue.append((r, send, on_fail))
        if self._ack_task is None or self._ack_task.done():
            self._ack_task = asyncio.ensure_future(self._drain_acks())

    async def _drain_acks(self) -> None:
        while self._ack_queue:
            r, send, on_fail = self._ack_queue.popleft()
            if isinstance(r, int):
                n = r
            else:
                try:
                    n = await r
                except Exception:
                    # dispatch failed inside the flusher; roll back and let
                    # the client retransmit
                    self.broker.metrics.inc("messages.dispatch_error")
                    if on_fail is not None:
                        try:
                            on_fail()
                        except Exception:
                            pass
                    self._signal_drained()
                    continue
            self._signal_drained()
            if send is None or self.state != "connected":
                continue
            try:
                send(n)
            except Exception:
                pass  # transport already torn down

    def _signal_drained(self) -> None:
        if self._ack_drained is not None:
            self._ack_drained.set()
            self._ack_drained = None

    # -- SUBSCRIBE / UNSUBSCRIBE ------------------------------------------
    async def _in_subscribe(self, p: pkt.Subscribe) -> None:
        # fold so extensions (topic rewrite) can transform the filter list
        filters = await self.hooks.arun_fold(
            "client.subscribe", (self.client_info(),), p.filters
        )
        # embedding filter riding the SUBSCRIBE user properties
        # (docs/semantic_routing.md): packet-level, applies to every
        # filter in the packet; malformed embeddings degrade to a plain
        # subscribe (counted) rather than failing the packet
        sem_parsed = None
        sem = getattr(self.broker, "semantic", None)
        if sem is not None and p.properties:
            try:
                sem_parsed = sem.parse_subscribe(p.properties)
            except (ValueError, TypeError):
                self.broker.metrics.inc("semantic.subscribe.rejected")
        rcs: List[int] = []
        pending: List[tuple] = []  # (rcs index, router-confirm future)
        for f, opts in filters:
            try:
                T.validate(f)
                group, real = T.parse_share(f)
                if group is not None and not self.config.caps.shared_subscription:
                    rcs.append(pkt.RC_SHARED_SUBSCRIPTIONS_NOT_SUPPORTED)
                    continue
                if T.wildcard(real if group else f) and not self.config.caps.wildcard_subscription:
                    rcs.append(pkt.RC_WILDCARD_SUBSCRIPTIONS_NOT_SUPPORTED)
                    continue
            except T.TopicValidationError:
                rcs.append(pkt.RC_TOPIC_FILTER_INVALID)
                continue
            allowed = await self.hooks.arun_fold(
                "client.authorize", (self.client_info(), "subscribe", f), "allow"
            )
            if allowed != "allow":
                if allowed == "disconnect":
                    # authz deny_action=disconnect applies to subscribe too
                    return self._close(
                        "not_authorized", pkt.RC_NOT_AUTHORIZED
                    )
                rcs.append(pkt.RC_NOT_AUTHORIZED)
                continue
            if self.session is None or self.state != "connected":
                return  # kicked while awaiting the authorize hook
            qos = min(opts.qos, self.config.caps.max_qos_allowed)
            opts.qos = qos
            mf = MP.mount(self.mountpoint, f)
            prior_opts = self.session.subscriptions.get(mf)
            existing = prior_opts is not None
            opts._existing = existing  # for retain_handling=1 semantics
            sub_kw = {}
            if (
                getattr(self.broker, "supports_raw_lane", False)
                and opts.qos == 0
                and not self.mountpoint
                and not self.hooks.callbacks("message.delivered")
                and not self.hooks.callbacks("delivery.completed")
            ):
                # QoS0 fast lane (worker fabric): the router ships
                # pre-serialized frames written straight to this socket
                # — only when no per-delivery work would be skipped
                sub_kw = {
                    "raw_sink": self.sink,
                    "raw_version": self.version,
                }
            if sem_parsed is not None:
                sub_kw["embedding"] = sem_parsed[0]
                sub_kw["sem_threshold"] = sem_parsed[1]
            r = self.broker.subscribe(
                self.client_id, self.client_id, mf, opts,
                self._make_deliverer(opts), **sub_kw,
            )
            if inspect.isawaitable(r):
                # worker-fabric broker: collect the router's confirm and
                # await AFTER the loop — all SUB frames are already on
                # the wire, so N filters cost one round-trip, not N (the
                # in-process broker registers synchronously, r is None)
                pending.append((len(rcs), mf, prior_opts, r))
            self.session.subscriptions[mf] = opts
            await self.hooks.arun(
                "session.subscribed", self.client_info(), mf, opts, self
            )
            rcs.append(qos)  # granted qos == success codes 0..2
        for idx, mf, prior, fut in pending:
            ok = await fut
            if self.session is None or self.state != "connected":
                return  # kicked/took-over while awaiting the router
            if ok is False:
                # router never confirmed (fabric link down / timeout):
                # the client must NOT believe it is subscribed
                rcs[idx] = pkt.RC_UNSPECIFIED_ERROR
                if prior is None:
                    # fresh subscribe: roll back the local registration
                    # so a late-registering SUB can't deliver to a
                    # client that was told it failed, and a later
                    # re-subscribe replays retained (rh=1) as fresh
                    self.broker.unsubscribe(self.client_id, mf)
                    if self.session.subscriptions.pop(mf, None) is not None:
                        await self.hooks.arun(
                            "session.unsubscribed", self.client_info(), mf
                        )
                else:
                    # failed UPGRADE of an established filter: the
                    # previously confirmed subscription stays live with
                    # its prior options (tearing it down would silently
                    # stop a flow the client still believes is active)
                    self.session.subscriptions[mf] = prior
                    self.broker.subscribe(
                        self.client_id, self.client_id, mf, prior,
                        self._make_deliverer(prior),
                    )
        self._send(pkt.Suback(packet_id=p.packet_id, reason_codes=rcs))

    def _make_deliverer(self, opts: pkt.SubOpts):
        def deliver(msg: Message, subopts: pkt.SubOpts) -> None:
            self.handle_deliver(msg, subopts)

        return deliver

    async def _in_unsubscribe(self, p: pkt.Unsubscribe) -> None:
        filters = await self.hooks.arun_fold(
            "client.unsubscribe", (self.client_info(),), p.filters
        )
        if self.session is None or self.state != "connected":
            return  # kicked while awaiting the unsubscribe hook
        rcs: List[int] = []
        for f in filters:
            mf = MP.mount(self.mountpoint, f)
            existed = self.broker.unsubscribe(self.client_id, mf)
            self.session.subscriptions.pop(mf, None)
            if existed:
                await self.hooks.arun("session.unsubscribed", self.client_info(), mf)
                rcs.append(pkt.RC_SUCCESS)
            else:
                rcs.append(pkt.RC_NO_SUBSCRIPTION_EXISTED)
        self._send(pkt.Unsuback(packet_id=p.packet_id, reason_codes=rcs))

    # -- DISCONNECT / close ------------------------------------------------
    def _in_disconnect(self, p: pkt.Disconnect) -> None:
        if p.reason_code == pkt.RC_SUCCESS:
            self.will = None  # normal disconnect discards the will
        expiry = p.properties.get("Session-Expiry-Interval")
        if expiry is not None and self.session is not None:
            self.session.config.expiry_interval = expiry
        self.state = "disconnected"
        self._close("normal")

    async def on_sock_closed(self, reason: str = "sock_closed") -> None:
        """Transport-level close (also the abnormal path: publish will)."""
        if self.state == "idle":
            return
        was_connected = self.state == "connected"
        self.state = "disconnected"
        try:
            if was_connected and self.will is not None:
                # apublish: the will is client-originated traffic, so it
                # must pass the same async extension chain (exhook
                # deny/rewrite) as an ordinary PUBLISH
                await self._publish_will()
            await self.hooks.arun(
                "client.disconnected",
                self.client_info(),
                self.disconnect_reason or reason,
            )
        finally:
            # registry cleanup must survive task cancellation mid-await
            # (listener.stop cancels connection tasks in their finally)
            self.cm.on_channel_closed(self, reason)

    async def _publish_will(self) -> None:
        w = self.will
        self.will = None
        try:
            T.validate(w.topic, kind="name")
        except T.TopicValidationError:
            return
        await self.broker.apublish(
            Message(
                topic=MP.mount(self.mountpoint, w.topic),
                payload=w.payload,
                qos=w.qos,
                retain=w.retain,
                from_client=self.client_id,
                properties=dict(w.properties),
            )
        )

    # -- outbound deliveries ----------------------------------------------
    def handle_deliver(self, msg: Message, opts: pkt.SubOpts) -> None:
        if self.mountpoint and msg.topic.startswith(self.mountpoint):
            # unmount on the way out (emqx_channel.erl:970-976)
            import copy

            msg = copy.copy(msg)
            msg.topic = MP.unmount(self.mountpoint, msg.topic)
        if self.state != "connected" or self.session is None:
            # connection-less window (e.g. between takeover begin/end):
            # park in the session queue for replay
            if self.session is not None and msg.qos > 0:
                self.session.mqueue.in_(msg)
            return
        # QoS0 fan-out fast path: serialize ONCE per (version, retain,
        # topic) and write the same bytes to every subscriber socket —
        # per-subscriber Publish construction + serialization was a top
        # per-delivery cost with fan-out 8 (the cache rides the Message
        # object, shared across its mount-variant copies)
        # retained-store replays are EXCLUDED: those Message objects live
        # as long as the store, and the cache would pin one serialized
        # copy per (version, retain, topic) variant against each of
        # millions of stored messages
        qos0 = (
            msg.qos == 0 or (opts is not None and opts.qos == 0)
        ) and not msg.headers.get("retained")
        sb = getattr(self.sink, "send_bytes", None)
        if qos0 and sb is not None:
            retain = (
                msg.retain
                if (opts is not None and opts.retain_as_published)
                else bool(msg.headers.get("retained"))
            )
            fb = getattr(msg, "_fb", None)
            if fb is None:
                fb = {}
                msg._fb = fb
            key = (self.version, retain, msg.topic)
            buf = fb.get(key)
            if buf is None:
                buf = fb[key] = serialize(
                    pkt.Publish(
                        topic=msg.topic,
                        payload=msg.payload,
                        qos=0,
                        retain=retain,
                        packet_id=None,
                        properties=dict(msg.properties),
                    ),
                    self.version,
                )
            self.hooks.run("message.delivered", self._ci_snapshot(), msg)
            sb(buf)
            self.broker.metrics.inc("packets.sent")
            self._delivery_completed(msg)
            return
        out = self.session.deliver(msg, opts)
        for q in out:
            self.hooks.run("message.delivered", self._ci_snapshot(), msg)
            if not (
                q.type == pkt.PUBLISH
                and q.qos
                and q.packet_id
                and not q.dup
                and self._send_pub_split(msg, q)
            ):
                self._send(q)
            if q.type == pkt.PUBLISH and q.qos == 0:
                # QoS0 completes at send; QoS1/2 complete at PUBACK/PUBCOMP
                # ('delivery.completed' hook, emqx_slow_subs.erl:25 parity)
                self._delivery_completed(msg)

    def _send_pub_split(self, msg: Message, q) -> bool:
        """QoS1/2 fan-out fast path: serialize the PUBLISH ONCE per
        (version, qos, retain, topic) as a head/tail pair around the
        packet-id slot (mqtt/slab_serializer.split_publish — bytes
        identical to frame.serialize) and emit each subscriber's frame
        as writelines([head, pid, tail]) — the payload is never copied
        per target. The cache rides the Message like the QoS0 `_fb`
        cache; retained-store replays are excluded for the same
        lifetime reason. Returns False to fall back to `_send`."""
        ws = getattr(self.sink, "send_segments", None)
        if ws is None or msg.headers.get("retained"):
            return False
        from emqx_tpu.mqtt import slab_serializer as SS

        fbq = getattr(msg, "_fbq", None)
        if fbq is None:
            fbq = {}
            msg._fbq = fbq
        key = (self.version, q.qos, q.retain, q.topic)
        ent = fbq.get(key)
        if ent is None:
            tb = q.topic.encode("utf-8")
            if len(tb) > 0xFFFF:
                return False  # _send raises the codec's exact error
            ent = fbq[key] = SS.split_publish(
                tb, q.payload, q.qos, q.retain, False, self.version,
                q.properties,
            )
        head, tail = ent
        ws([head, SS.pid_bytes(q.packet_id), tail])
        self.broker.metrics.inc("packets.sent")
        self.broker.metrics.inc("dispatch.serialize.frames")
        return True

    def _delivery_completed(self, msg: Message) -> None:
        self.hooks.run(
            "delivery.completed",
            self._ci_snapshot(),
            msg,
            time.time() - msg.timestamp,
        )

    # -- timers ------------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> None:
        """Periodic work: QoS retry + awaiting_rel expiry. `now` is a
        monotonic-clock reading (elapsed-time questions only — wall
        steps must not mass-expire windows)."""
        if self.session is None:
            return
        if not self.session.inflight.store_managed:
            # store-managed windows retransmit from the session store's
            # sweep (device scan riding a launch, or the host fallback)
            # through _store_resend — never from a per-channel walk
            for q in self.session.retry():
                self._send(q)
        now = now or time.monotonic()
        timeout = self.session.config.await_rel_timeout
        expired = [
            pid
            for pid, ts in self.session.awaiting_rel.items()
            if now - ts > timeout
        ]
        for pid in expired:
            self.session.release_rel(pid)

    def _store_resend(self, pid: int, state: int, msg) -> bool:
        """Redelivery sink for the session store's retry sweeps: dup
        PUBLISH for the publish phase, PUBREL for the rel phase. Returns
        False (no stamp refresh) when this channel can't transmit."""
        if self.state != "connected" or self.session is None:
            return False
        from emqx_tpu.ops.session_table import ST_PUBREL

        if state == ST_PUBREL:
            rel = pkt.PubAck(packet_id=pid)
            rel.type = pkt.PUBREL
            self._send(rel)
            return True
        if msg is None:
            return False
        self._send(
            self.session._publish_packet(msg, msg.qos, pid, dup=True)
        )
        return True

    def _store_resend_batch(self, items) -> List[bool]:
        """Batched twin of `_store_resend` for the session store's sweep
        floods: ALL of this channel's due rows serialize in ONE slab
        pass (mqtt/slab_serializer — vectorized headers/varints, frames
        byte-identical to the per-packet path) and land on the socket as
        a `writelines` of memoryviews. Returns per-item sent flags (all
        False when the channel can't transmit)."""
        if self.state != "connected" or self.session is None:
            return [False] * len(items)
        from emqx_tpu.mqtt import slab_serializer as SS
        from emqx_tpu.ops.session_table import ST_PUBREL

        sent = [True] * len(items)
        pubs = []  # (item index, serializer tuple)
        segs: List = []  # per-frame segments in item order
        seg_slot: List[int] = []  # index into segs for each publish
        v5 = self.version == pkt.MQTT_V5
        for i, (pid, state, msg) in enumerate(items):
            if state == ST_PUBREL:
                segs.append(SS.pubrel_frame(pid))
                continue
            if msg is None:
                sent[i] = False
                continue
            pb = None
            if v5:
                props = getattr(msg, "properties", None)
                pb = SS.encode_properties(props) if props else None
            pubs.append(
                (msg.topic_bytes(), msg.payload_view(), msg.qos,
                 msg.retain, True, pid, pb)  # dup=True: retransmit
            )
            seg_slot.append(len(segs))
            segs.append(None)  # patched with the slab view below
        if pubs:
            slab, offs = SS.serialize_pub_slab(pubs, self.version)
            for k, mv in enumerate(SS.frames_of(slab, offs)):
                segs[seg_slot[k]] = mv
        segs = [s for s in segs if s is not None]
        if not segs:
            return sent
        ws = getattr(self.sink, "send_segments", None)
        try:
            if ws is not None:
                ws(segs)
            else:
                self.sink.send_bytes(b"".join(segs))
        except Exception:
            return [False] * len(items)
        m = self.broker.metrics
        m.inc("packets.sent", len(segs))
        m.inc("dispatch.serialize.batches")
        m.inc("dispatch.serialize.frames", len(segs))
        m.inc("dispatch.serialize.bytes", sum(len(s) for s in segs))
        return sent

    # -- takeover / kick ---------------------------------------------------
    def kick(self, reason: str) -> Optional[Session]:
        """Forcibly close; returns the session for takeover if requested."""
        session = self.session
        if self.state == "connected":
            rc = (
                pkt.RC_SESSION_TAKEN_OVER
                if reason == "takenover"
                else pkt.RC_ADMINISTRATIVE_ACTION
            )
            if self.version == pkt.MQTT_V5:
                self._send(pkt.Disconnect(reason_code=rc))
        self.state = "disconnected"
        self.disconnect_reason = reason
        self.session = None
        self.sink.close(reason)
        return session
