"""Application assembly + lifecycle: config -> running broker.

The emqx_machine analog (apps/emqx_machine/src/emqx_machine_boot.erl:
dependency-ordered app boot, signal handling): builds the broker kernel,
extensions, listeners, management API and periodic housekeeping from one
`AppConfig`, starts them in dependency order, and tears them down cleanly.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import List, Optional

from emqx_tpu.broker.auth import AuthChain, BuiltinDatabase, JwtAuth
from emqx_tpu.broker.authz import AclRule, Authorizer
from emqx_tpu.broker.auto_subscribe import AutoSubscribe, AutoSubscribeTopic
from emqx_tpu.broker.banned import Banned, Flapping
from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.channel import ChannelConfig
from emqx_tpu.broker.cm import ChannelManager
from emqx_tpu.broker.delayed import DelayedPublish
from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.broker.message import Message
from emqx_tpu.broker.retainer import Retainer
from emqx_tpu.broker.rewrite import RewriteRule, TopicRewrite
from emqx_tpu.broker.router import Router
from emqx_tpu.broker.shared_sub import SharedSub
from emqx_tpu.config.schema import AppConfig
from emqx_tpu.ops.matcher import MatcherConfig
from emqx_tpu.transport.listener import ListenerConfig, Listeners
from emqx_tpu.utils.node import node_name, set_node_name


def _register_builtin_gateways(registry) -> None:
    """Built-in protocol gateway types (apps/emqx_gateway/src/* impls)."""
    from emqx_tpu.gateway.coap import CoapGateway
    from emqx_tpu.gateway.exproto import ExprotoGateway
    from emqx_tpu.gateway.lwm2m import Lwm2mGateway
    from emqx_tpu.gateway.mqttsn import SnGateway
    from emqx_tpu.gateway.stomp import StompGateway

    registry.register_type("stomp", StompGateway)
    registry.register_type("mqttsn", SnGateway)
    registry.register_type("exproto", ExprotoGateway)
    registry.register_type("coap", CoapGateway)
    registry.register_type("lwm2m", Lwm2mGateway)


def attach_guards(hooks: Hooks, c: AppConfig):
    """Banned + flapping admission guards (emqx_banned / emqx_flapping)."""
    banned = Banned()
    banned.attach(hooks)
    flapping = (
        Flapping(
            banned,
            max_count=c.flapping.max_count,
            window=c.flapping.window_time,
            ban_time=c.flapping.ban_time,
        )
        if c.flapping.enable
        else None
    )
    if flapping:
        flapping.attach(hooks)
    return banned, flapping


def attach_authn(hooks: Hooks, c: AppConfig, channel_config: ChannelConfig):
    """Authn chain + SCRAM enhanced auth from config (emqx_authn analog).

    Shared by BrokerApp and the connection workers (transport/workers.py):
    each worker rebuilds the same chain from the same config, so admission
    semantics don't depend on which process accepted the socket."""
    scram = None
    authn = None
    if c.authn.enable:
        providers = []
        if c.authn.users:
            db = BuiltinDatabase(
                user_id_type=c.authn.user_id_type,
                algo=c.authn.password_hash,
            )
            for u in c.authn.users:
                db.add_user(u.user_id, u.password, u.is_superuser)
            providers.append(db)
        if c.authn.jwt_secret:
            providers.append(
                JwtAuth(c.authn.jwt_secret.encode(), c.authn.jwt_verify_claims)
            )
        if c.authn.http_url:
            from emqx_tpu.auth.http import HttpAuthProvider

            providers.append(
                HttpAuthProvider(
                    c.authn.http_url,
                    method=c.authn.http_method,
                    timeout=c.authn.http_timeout,
                )
            )
        if c.authn.jwks_endpoint:
            from emqx_tpu.auth.jwks import JwksAuthProvider

            providers.append(
                JwksAuthProvider(
                    c.authn.jwks_endpoint,
                    refresh_interval=c.authn.jwks_refresh_interval,
                    verify_claims=c.authn.jwks_verify_claims,
                )
            )
        authn = AuthChain(providers, allow_anonymous=c.authn.allow_anonymous)
        authn.attach(hooks)
    if c.authn.scram_enable:
        from emqx_tpu.auth.scram import ScramAuthenticator

        scram = ScramAuthenticator(iterations=c.authn.scram_iterations)
        for u in c.authn.scram_users:
            scram.add_user(u.user_id, u.password, u.is_superuser)
        channel_config.enhanced_auth[scram.METHOD] = scram
    return authn, scram


def attach_authz(hooks: Hooks, c: AppConfig):
    """ACL rules + file ACL + network authz sources (emqx_authz analog)."""
    authz_rules = [BrokerApp._acl_rule(r) for r in c.authz.rules]
    if c.authz.acl_file:
        from emqx_tpu.auth.file_acl import load as load_acl_file

        authz_rules.extend(load_acl_file(c.authz.acl_file))
    authz_sources = []
    if c.authz.http_url:
        from emqx_tpu.auth.http import HttpAuthzSource

        authz_sources.append(
            HttpAuthzSource(
                c.authz.http_url,
                method=c.authz.http_method,
                timeout=c.authz.http_timeout,
            )
        )
    authz = Authorizer(
        rules=authz_rules,
        no_match=c.authz.no_match,
        deny_action=c.authz.deny_action,
        sources=authz_sources,
    )
    authz.attach(hooks)
    return authz


def build_guard_hooks(c: AppConfig, hooks: Hooks) -> ChannelConfig:
    """Worker-process hook stack: the admission-relevant slice of the
    BrokerApp wiring (guards + authn + authz) against a fresh Hooks, plus
    the ChannelConfig the worker's channels run with. Everything else
    (retainer, rules, bridges, cluster) lives only in the router process."""
    channel_config = ChannelConfig(caps=c.mqtt, session=c.session)
    attach_guards(hooks, c)
    attach_authn(hooks, c, channel_config)
    attach_authz(hooks, c)
    return channel_config


class BrokerApp:
    def __init__(self, config: Optional[AppConfig] = None):
        self.config = config or AppConfig()
        c = self.config
        if c.node.name:
            set_node_name(c.node.name)

        from emqx_tpu.config.schema import LogConfig
        from emqx_tpu.observe import logfmt

        # logging is process-global: a second in-process app (cluster
        # tests, embedded brokers) with DEFAULT log config must not
        # clobber an earlier app's explicit handler setup
        if logfmt._handler is None or c.log != LogConfig():
            logfmt.setup_logging(c.log.level, c.log.formatter, c.log.to_file)

        self.hooks = Hooks()
        self.router = Router(
            matcher_config=MatcherConfig(
                max_levels=c.router.max_levels,
                frontier=c.router.frontier,
                max_matches=c.router.max_matches,
                max_bytes=c.router.max_bytes,
                fanout_compact=c.router.fanout_compact,
                fanout_slots=c.router.fanout_slots,
                sub_table=c.router.sub_table,
                sparse_gather=c.router.sparse_gather,
                donate_buffers=c.router.donate_buffers,
                jit_cache_max=c.router.jit_cache_max,
            ),
            min_tpu_batch=c.router.min_tpu_batch,
            enable_tpu=c.router.enable_tpu,
        )
        self.broker = Broker(router=self.router, hooks=self.hooks)
        self.broker.shared = SharedSub(strategy=c.shared_subscription.strategy)
        if (
            c.router.enable_tpu
            and c.router.mesh_shape[0] > 0
            and c.router.mesh_shape[1] > 0
        ):
            # SPMD serving: the dispatch path runs dist_shape_route_step
            # over a (dp, tp) device mesh (parallel/mesh.py)
            from emqx_tpu.parallel.mesh import make_mesh

            dp, tp = c.router.mesh_shape
            self.broker.mesh = make_mesh(dp * tp, tp=tp)
            # every table owner shards through the same mesh: the lazy
            # match-only engine (Router.matcher) and the retained replay
            # index pick it up from here (segment-manager placements)
            self.router.mesh = self.broker.mesh
            # a sparse subscriber table partitions its slot column over
            # the 'tp' axis; setting the shard count up front avoids a
            # re-shard rebuild on the first prepare
            self.broker.subtab.set_shards(tp)
        if c.semantic.enable:
            # semantic routing plane (docs/semantic_routing.md):
            # embedding-filter subscriptions fused into the serving
            # launch; attached BEFORE the first dispatch builds the
            # device engine so the engine binds the semantic table
            from emqx_tpu.broker.semantic import SemanticRouting

            self.broker.semantic = SemanticRouting(
                dim=c.semantic.dim,
                topk=c.semantic.topk,
                threshold=c.semantic.threshold,
                dtype=c.semantic.dtype,
                shards=(
                    c.router.mesh_shape[1]
                    if self.broker.mesh is not None
                    else 1
                ),
                metrics=self.broker.metrics,
            )
        self.cm = ChannelManager(self.broker)
        # device-resident session store (broker/session_store.py): the
        # inflight/QoS state tables ride the same segment machinery as
        # subscriptions; ack clears fuse into serving launches. The
        # host-dict path stays the fallback (knob off = unchanged)
        if c.session.device_store and c.router.enable_tpu:
            from emqx_tpu.broker.session_store import SessionStore

            self.session_store = SessionStore(
                capacity=c.session.store_capacity,
                sweep_slots=c.session.store_sweep_slots,
                retry_interval=c.session.retry_interval,
                metrics=self.broker.metrics,
                mesh=self.broker.mesh,
            )
            self.broker.session_store = self.session_store
            self.cm.session_store = self.session_store
        else:
            self.session_store = None
        self.channel_config = ChannelConfig(caps=c.mqtt, session=c.session)
        # populated below once authn config is read (SCRAM enhanced auth)
        # rate limiting + overload protection (reference: emqx_limiter,
        # emqx_olp; wired into listeners like the esockd limiter adapter)
        from emqx_tpu.broker.limiter import LimiterServer
        from emqx_tpu.broker.olp import Olp
        from emqx_tpu.transport.listener import TransportContext

        self.limiters = LimiterServer(c.limiter)
        self.olp = Olp(
            enable=c.olp.enable,
            lag_watermark_ms=c.olp.lag_watermark_ms,
            cooldown=c.olp.cooldown,
            metrics=self.broker.metrics,
        )
        # fault injection (observe/faults.py): the process-wide injector
        # gets this broker's metrics for faults.injected accounting;
        # config-armed rules (default off) load here, runtime arming
        # goes through GET/POST /api/v5/faults
        from emqx_tpu.observe.faults import default_faults

        self.faults = default_faults
        self.faults.metrics = self.broker.metrics
        if c.faults.enable:
            for fr in c.faults.rules:
                self.faults.arm(
                    fr.site,
                    mode=fr.mode,
                    probability=fr.probability,
                    nth=fr.nth,
                    max_fires=fr.max_fires,
                    delay_ms=fr.delay_ms,
                )
        # device profiling + performance provenance (observe/profiler.py,
        # observe/provenance.py): the process-wide profiler gets this
        # broker's metrics; captures are REST-armed (POST /api/v5/profile)
        # and the housekeeping tick enforces their duration/byte bounds.
        # The hardware fingerprint gauges let dashboards refuse to
        # overlay runs from different silicon (proxy=1 means non-TPU).
        from emqx_tpu.observe import provenance
        from emqx_tpu.observe.profiler import default_profiler

        self.profiler = default_profiler
        self.profiler.metrics = self.broker.metrics
        self.profiler.trace_dir = c.observe.profile_trace_dir
        self.profiler.max_seconds = float(c.observe.profile_max_seconds)
        self.profiler.max_bytes = int(c.observe.profile_max_bytes)
        fp = provenance.fingerprint()
        self.broker.metrics.gauge_set(
            "provenance.proxy", 1 if fp["proxy"] else 0
        )
        self.broker.metrics.gauge_set(
            "provenance.device.count", fp["device_count"]
        )
        if c.force_gc.enable:
            from emqx_tpu.transport.congestion import ForcedGC

            _gc_count, _gc_bytes = c.force_gc.count, c.force_gc.bytes
            make_forced_gc = lambda: ForcedGC(_gc_count, _gc_bytes)  # noqa: E731
        else:
            make_forced_gc = None
        self.transport_ctx = TransportContext(
            limiters=self.limiters,
            olp=self.olp,
            alarms=None,  # filled in below once AlarmManager exists
            make_forced_gc=make_forced_gc,
        )
        self.listeners = Listeners(self.broker, self.cm, ctx=self.transport_ctx)
        if self.limiters.limited("message_routing"):
            # message_routing limiter: overload-drop at the publish gate
            # (the reference's routing limiter sheds load rather than queue)
            routing_limiter = self.limiters.connect("message_routing")

            def _routing_gate(msg, acc=None):
                m = acc if acc is not None else msg
                if not routing_limiter.try_acquire(1):
                    self.broker.metrics.inc("limiter.dropped.message_routing")
                    m.headers["allow_publish"] = False
                return ("ok", m)

            self.hooks.add(
                "message.publish", _routing_gate, priority=1000,
                tag="limiter.message_routing",
            )

        # extensions (reference L4, SURVEY.md §1)
        self.banned, self.flapping = attach_guards(self.hooks, c)

        self.retainer = Retainer(
            max_retained=c.retainer.max_retained_messages,
            max_payload=c.retainer.max_payload_size,
            device_threshold=c.retainer.device_threshold,
            enable_device=c.router.enable_tpu,
        )
        self.retainer.enabled = c.retainer.enable
        self.retainer.mesh = self.broker.mesh
        self.retainer.attach(self.hooks)

        self.delayed = DelayedPublish(
            self.broker, max_messages=c.delayed.max_delayed_messages
        )
        self.delayed.enabled = c.delayed.enable
        self.delayed.attach(self.hooks)

        if c.rewrite:
            TopicRewrite(
                [
                    RewriteRule(r.action, r.source_topic, r.re, r.dest_topic)
                    for r in c.rewrite
                ]
            ).attach(self.hooks)

        if c.auto_subscribe:
            AutoSubscribe(
                [
                    AutoSubscribeTopic(filter=s.topic, qos=s.qos)
                    for s in c.auto_subscribe
                ]
            ).attach(self.hooks)

        self.authn, self.scram = attach_authn(
            self.hooks, c, self.channel_config
        )

        # TLS-PSK identity store (emqx_psk analog)
        self.psk = None
        if c.psk.enable:
            from emqx_tpu.auth.psk import PskStore

            self.psk = PskStore()
            for ident, secret in c.psk.identities.items():
                self.psk.insert(ident, secret)
            if c.psk.file:
                self.psk.import_file(c.psk.file)
            self.transport_ctx.psk = self.psk

        # rule engine (reference L4: emqx_rule_engine)
        from emqx_tpu.rules.engine import Console, Republish, RuleEngine

        self.rule_engine = RuleEngine(self.broker)
        self.rule_engine.attach(self.hooks)
        if c.semantic.enable and c.semantic.rule_predicates:
            # device-compiled WHERE predicates (rules/compile.py):
            # eligible rules filter at match rate inside the serving
            # launch instead of post-dispatch Python rate
            self.rule_engine.attach_device()
        for spec in c.rules:
            outputs = []
            for o in spec.outputs or [None]:
                if o is None or o.function == "console":
                    outputs.append(Console())
                elif o.function == "bridge":
                    outputs.append(self._bridge_output(str(o.args.get("id", ""))))
                else:
                    a = o.args
                    outputs.append(
                        Republish(
                            topic=str(a.get("topic", "")),
                            payload=str(a.get("payload", "${payload}")),
                            qos=int(a.get("qos", 0)),
                            retain=bool(a.get("retain", False)),
                        )
                    )
            rule = self.rule_engine.create_rule(
                spec.id, spec.sql, outputs, spec.description
            )
            rule.enabled = spec.enable

        self.authz = attach_authz(self.hooks, c)

        # observability (reference L5 aux: SURVEY.md §5.1/§5.5)
        from emqx_tpu.observe.alarm import AlarmManager, FallbackRateWatch
        from emqx_tpu.observe.event_message import EventMessage
        from emqx_tpu.observe.exporters import StatsdExporter
        from emqx_tpu.observe.monitors import OsMon, SysMon, VmMon
        from emqx_tpu.observe.slow_subs import SlowSubs
        from emqx_tpu.observe.topic_metrics import TopicMetrics
        from emqx_tpu.observe.trace import TraceManager

        ob = c.observe
        self.alarms = AlarmManager(
            publish=lambda topic, payload: self.broker.publish(
                Message(topic=topic, payload=payload)
            ),
            size_limit=ob.alarm_size_limit,
            validity_period=ob.alarm_validity_period,
        )
        self.transport_ctx.alarms = self.alarms
        self.fallback_watch = (
            FallbackRateWatch(
                self.alarms,
                self.broker.metrics,
                threshold=ob.tpu_fallback_alarm_threshold,
                window=ob.tpu_fallback_alarm_window,
                min_rows=ob.tpu_fallback_alarm_min_rows,
            )
            if ob.tpu_fallback_alarm_enable and c.router.enable_tpu
            else None
        )
        self.sys_mon = SysMon(self.alarms) if ob.sys_mon_enable else None
        self.os_mon = OsMon(self.alarms) if ob.os_mon_enable else None
        self.vm_mon = VmMon(self.alarms) if ob.vm_mon_enable else None
        self.slow_subs = SlowSubs(
            threshold_ms=ob.slow_subs.threshold_ms,
            top_k=ob.slow_subs.top_k_num,
            expire_interval=ob.slow_subs.expire_interval,
        )
        self.slow_subs.enabled = ob.slow_subs.enable
        self.slow_subs.attach(self.hooks)

        # license (lib-ee/emqx_license analog): verify + expiry alarms +
        # connection gate; community/unlimited when no key is configured
        from emqx_tpu import license as lic_mod

        if c.license.key:
            if not c.license.pubkey_n:
                from emqx_tpu.config.schema import ConfigError

                raise ConfigError(
                    "license.key is set but license.pubkey_n (hex modulus "
                    "of the verifier key) is missing"
                )
            pub = (int(c.license.pubkey_n, 16), c.license.pubkey_e)
            self.license = lic_mod.LicenseChecker(
                lic_mod.parse(c.license.key, pub), alarms=self.alarms
            )
        else:
            self.license = lic_mod.LicenseChecker(alarms=self.alarms)
        self.license.attach(self.hooks, self.cm)
        self.topic_metrics = TopicMetrics()
        self.topic_metrics.attach(self.hooks)
        self.event_message = EventMessage(
            self.broker,
            enabled={
                name
                for name in (
                    "client_connected",
                    "client_disconnected",
                    "session_subscribed",
                    "session_unsubscribed",
                    "message_delivered",
                    "message_acked",
                    "message_dropped",
                )
                if getattr(ob.event_message, name)
            },
        )
        self.event_message.attach(self.hooks)
        self.trace = TraceManager(base_dir=ob.trace_dir)
        self.trace.attach(self.hooks)
        # causal span tracing (observe/spans.py): head-sampled publish ->
        # batch -> device-step -> deliver spans; clients under an active
        # TraceSpec always sample (self.trace.should_sample)
        if ob.trace_spans_enable:
            from emqx_tpu.observe.spans import OtlpFileExporter, SpanRecorder

            self.spans = SpanRecorder(
                metrics=self.broker.metrics,
                sample_rate=ob.trace_sample_rate,
                sample_clients=ob.trace_sample_clients,
                sample_topics=ob.trace_sample_topics,
                seed=ob.trace_sample_seed,
                ring=ob.trace_span_ring,
                exporter=(
                    OtlpFileExporter(ob.trace_span_file)
                    if ob.trace_span_file
                    else None
                ),
                always_sample=self.trace.should_sample,
            )
            self.broker.spans = self.spans
        else:
            self.spans = None
        # graceful-degradation ladder (broker/degrade.py): device-path
        # breaker + retry policy; transitions emit degrade.* series and
        # span events so traces show WHY a message took the slow path
        if c.degrade.enable:
            from emqx_tpu.broker.degrade import DegradeController

            self.degrade = DegradeController(
                metrics=self.broker.metrics,
                spans=self.spans,
                max_retries=c.degrade.max_retries,
                backoff_base_s=c.degrade.backoff_base_ms / 1e3,
                backoff_max_s=c.degrade.backoff_max_ms / 1e3,
                failure_threshold=c.degrade.failure_threshold,
                open_secs=c.degrade.open_secs,
                probe_successes=c.degrade.probe_successes,
                shed_queue_batches=c.degrade.shed_queue_batches,
            )
            self.broker.degrade = self.degrade
        else:
            self.degrade = None
        # SLO-driven adaptive batching (broker/slo.py): the ingest
        # window becomes a controlled variable holding a p99 target;
        # the graded backpressure ladder (widen -> defer -> shed)
        # replaces the binary shed cliff. Attached to BatchIngest (and
        # the retained-storm feed) in start().
        if c.slo.enable and c.router.ingest_enable and c.router.enable_tpu:
            from emqx_tpu.broker.slo import SloController

            self.slo = SloController(
                metrics=self.broker.metrics,
                target_p99_ms=c.slo.target_p99_ms,
                min_window_us=c.slo.min_window_us,
                max_window_us=c.slo.max_window_us,
                initial_window_us=c.router.ingest_window_us,
                eval_interval_s=c.slo.eval_interval_ms / 1e3,
                min_samples=c.slo.min_samples,
                gain=c.slo.gain,
                hysteresis=c.slo.hysteresis,
                ladder_patience=c.slo.ladder_patience,
                defer_max_s=c.slo.defer_max_ms / 1e3,
                starvation_s=c.slo.starvation_ms / 1e3,
                shed_hard_mult=c.slo.shed_hard_mult,
                olp=self.olp,
                spans=self.spans,
            )
        else:
            self.slo = None
        self.slo_watch = None
        if self.slo is not None and c.slo.alarm_enable:
            from emqx_tpu.observe.alarm import SloViolationWatch

            # level-triggered page on SUSTAINED target misses (the
            # controller absorbs transient ones) — FallbackRateWatch's
            # sibling, checked from housekeeping
            self.slo_watch = SloViolationWatch(
                self.alarms,
                self.broker.metrics,
                threshold=c.slo.alarm_threshold,
                window=c.slo.alarm_window,
                min_windows=c.slo.alarm_min_windows,
            )
        # device runtime telemetry (observe/device_watch.py): compile /
        # retrace watch + HBM & transfer gauges, polled from housekeeping
        if c.router.enable_tpu:
            from emqx_tpu.observe.alarm import RetraceStormWatch
            from emqx_tpu.observe.device_watch import DeviceWatch

            self.device_watch = DeviceWatch(self.broker.metrics)
            self.retrace_watch = (
                RetraceStormWatch(
                    self.alarms,
                    self.broker.metrics,
                    threshold=ob.retrace_alarm_threshold,
                    window=ob.retrace_alarm_window,
                    warmup=ob.retrace_alarm_warmup,
                    sustain=ob.retrace_alarm_sustain,
                )
                if ob.retrace_alarm_enable
                else None
            )
        else:
            self.device_watch = None
            self.retrace_watch = None
        # background segment compaction (ops/segments.py): housekeeping
        # merges the shape-index hot segment into the packed table and
        # proactively grows the subscriber bitmaps on the compaction
        # executor — the subscribe path never pays an O(table) rebuild
        if c.router.enable_tpu:
            from emqx_tpu.ops.segments import SegmentCompactor

            self.segment_compactor = SegmentCompactor(
                metrics=self.broker.metrics,
                interval_s=c.router.compact_interval_s,
            )
        else:
            self.segment_compactor = None
        self.statsd = (
            StatsdExporter(
                self.broker.metrics,
                host=ob.statsd.server_host,
                port=ob.statsd.server_port,
                interval=ob.statsd.flush_interval,
            )
            if ob.statsd.enable
            else None
        )

        # durability (persistent sessions + disc-copies analog, SURVEY §5.4)
        if c.durability.enable:
            from emqx_tpu.broker.persistent_session import (
                DurableState,
                SessionPersistence,
            )
            from emqx_tpu.storage.kv import FileKv

            import os as _os

            from emqx_tpu.storage.wal import MessageWal

            kv = FileKv(c.durability.data_dir, fsync=c.durability.fsync)
            self.session_persistence = SessionPersistence(
                self.broker,
                self.cm,
                kv,
                self.channel_config.session,
                wal=MessageWal(
                    _os.path.join(c.durability.data_dir, "messages.wal"),
                    fsync=c.durability.fsync,
                ),
            )
            self.session_persistence.attach(self.hooks)
            segments = None
            if c.durability.segment_snapshot:
                # rolling-upgrade fast path: the device-table host state
                # (route index + bitmaps) checkpoints as a sidecar pickle
                # so a replacement process restores million-entry tables
                # instead of replaying every subscribe
                from emqx_tpu.ops.segments import SegmentStateSnapshot

                def _cap_segments():
                    state = {
                        "router": self.broker.router,
                        "subtab": self.broker.subtab,
                        "grouptab": self.broker.grouptab,
                    }
                    if self.session_store is not None:
                        # mass session resume = segment replay: the
                        # whole inflight/QoS table checkpoints as
                        # arrays; restore re-arms every window with one
                        # upload, zero per-session objects rebuilt
                        state["session_store"] = (
                            self.session_store.capture()
                        )
                    return state

                def _install_segments(state):
                    self.broker.router = state["router"]
                    self.broker.subtab = state["subtab"]
                    self.broker.grouptab = state["grouptab"]
                    if (
                        self.session_store is not None
                        and state.get("session_store") is not None
                    ):
                        self.session_store.install(
                            state["session_store"]
                        )
                    self.broker._device = None  # rebuilt on next batch

                segments = SegmentStateSnapshot(
                    _os.path.join(c.durability.data_dir, "segments.pkl"),
                    capture=_cap_segments,
                    install=_install_segments,
                )
            self.durable_state = DurableState(
                kv,
                retainer=self.retainer if c.retainer.enable else None,
                delayed=self.delayed if c.delayed.enable else None,
                banned=self.banned,
                degrade=self.degrade,
                segments=segments,
            )
        else:
            self.session_persistence = None
            self.durable_state = None

        # exhook gRPC sidecars (reference: emqx_exhook, SURVEY.md §2.2)
        if c.exhook:
            from emqx_tpu import __version__
            from emqx_tpu.exhook.manager import ExhookManager, ExhookServer

            self.exhook = ExhookManager(version=__version__)
            for spec in c.exhook:
                self.exhook.add_server(
                    ExhookServer(
                        name=spec.name or spec.url,
                        url=spec.url,
                        timeout=spec.timeout,
                        failed_action=spec.failed_action,
                    )
                )
            self.exhook.attach(self.hooks)
        else:
            self.exhook = None

        self.mgmt_server = None  # set by start() when dashboard.enable
        self.gateways = None  # GatewayRegistry, set by start() when configured
        self.bridges = None  # BridgeManager, set by start() when configured
        self.plugins = None  # PluginManager (lazy)
        self.telemetry = None  # Telemetry, set by start()
        self.config_handler = self._make_config_handler()
        self._tasks: List[asyncio.Task] = []
        self.worker_pools: List = []  # WorkerPool, set by start()
        self.started_at: Optional[float] = None

    @staticmethod
    def _acl_rule(spec) -> AclRule:
        who = spec.who
        if isinstance(who, str) and ":" in who:
            k, v = who.split(":", 1)
            who = {k: v}
        return AclRule(spec.permit, who, spec.action, list(spec.topics))

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> None:
        c = self.config
        # config-driven clustering (ekka autocluster analog): bus + node
        # wrap the broker BEFORE listeners accept, so the first subscribe
        # already replicates its route
        self.cluster_bus = None
        self.cluster_node = None
        if c.cluster.enable:
            from emqx_tpu.cluster.node import ClusterNode
            from emqx_tpu.cluster.tcp_transport import TcpBus

            self.cluster_bus = TcpBus(
                node_name(),
                host=c.cluster.bind,
                port=c.cluster.listen_port,
                send_retries=c.cluster.send_retries,
                send_backoff_s=c.cluster.send_backoff_ms / 1e3,
                send_deadline_s=c.cluster.send_deadline_s,
                metrics=self.broker.metrics,
                degrade=self.degrade,
            )
            self.cluster_node = ClusterNode(
                node_name(),
                self.cluster_bus,
                broker=self.broker,
                loop=asyncio.get_running_loop(),
            )
            self.broker.cluster = self.cluster_node
            if self.broker.mesh is not None:
                # scale-out serving: advertise this node's slice of the
                # global subscriber-lane space; shard ownership + the
                # node-loss re-own ladder live in cluster/route_sync.py
                idx, total = c.cluster.shard_slice
                self.cluster_node.attach_mesh_slice(
                    c.router.mesh_shape, idx, total
                )
            if c.retainer.enable:
                # retained set/clear replicate cluster-wide + join-time
                # bootstrap (emqx_retainer_mnesia parity)
                self.cluster_node.attach_retainer(self.retainer, self.hooks)
            for s in c.cluster.seeds:
                self.cluster_bus.add_peer(s.node, s.host, s.port)
            if c.cluster.seeds:
                self._tasks.append(
                    asyncio.get_running_loop().create_task(
                        self._cluster_join([s.node for s in c.cluster.seeds])
                    )
                )
            # liveness: periodic heartbeat + failure detection (the
            # tests drive Membership.heartbeat() manually; a live app
            # needs the ticker)
            from emqx_tpu.cluster.membership import HEARTBEAT_INTERVAL

            async def _beat():
                while True:
                    await asyncio.sleep(HEARTBEAT_INTERVAL)
                    node = self.cluster_node
                    if node is None:
                        return
                    try:
                        await asyncio.get_running_loop().run_in_executor(
                            None, node.membership.heartbeat
                        )
                    except Exception:
                        pass

            self._tasks.append(
                asyncio.get_running_loop().create_task(_beat())
            )
        # publish batch aggregator: live connection traffic rides the device
        # route path (broker/ingest.py) once the loop is running
        if c.router.ingest_enable and c.router.enable_tpu:
            from emqx_tpu.broker.ingest import BatchIngest

            self.broker.ingest = BatchIngest(
                self.broker,
                max_batch=c.router.ingest_max_batch,
                window_us=c.router.ingest_window_us,
                pipeline=c.router.ingest_pipeline,
                olp=self.olp,
                slo=self.slo,
                qos0_low=self.slo is not None and c.slo.qos0_low_lane,
            )
            self.broker.ingest.start()
            if c.retainer.enable and c.retainer.storm_ride:
                # wildcard-subscribe replay storms ride the serving
                # pipeline's fused launch (broker/retained_feed.py) —
                # single-device AND mesh mode (the mesh engine fuses
                # them into dist_fused_step, chunk rows over 'dp');
                # the device retained index attaches lazily on first
                # eligible insert, so wire the feed through a factory
                from emqx_tpu.broker.retained_feed import RetainedStormFeed

                self.retainer.ensure_device()
                if self.retainer._device is not None:
                    feed = RetainedStormFeed(
                        self.retainer._device,
                        metrics=self.broker.metrics,
                        window_s=c.retainer.storm_window_us / 1e6,
                    )
                    # retained replays are tagged low-priority: on the
                    # SLO ladder's defer rung they sit launches out
                    # instead of deepening an already-violating tail
                    feed.slo = self.slo
                    self.retainer.storm_feed = feed
                    self.broker.retained_feed = feed
        # restore durable state BEFORE listeners accept clients
        if self.session_persistence is not None:
            restored = self.session_persistence.restore()
            if restored:
                self.broker.metrics.gauge_set("sessions.restored", restored)
        if self.durable_state is not None:
            self.durable_state.restore()
        if self.broker.ingest is not None:
            # pre-warm the route_step kernel BEFORE listeners accept (but
            # AFTER restore, so restored subscriptions set the table shapes
            # the compile keys on): first-contact compile on a real chip is
            # tens of seconds and must not land on live publishers
            try:
                dev = self.broker._device_router()
                args = dev.prepare()
                await asyncio.get_running_loop().run_in_executor(
                    None,
                    dev.route_prepared,
                    args,
                    ["warmup/a"] * max(1, c.router.min_tpu_batch),
                )
            except Exception:
                logging.getLogger("emqx_tpu").exception(
                    "device route warmup failed; serving with cold kernel"
                )
        for spec in c.listeners:
            chan_cfg = self.channel_config
            if spec.mountpoint:
                # per-listener channel config: same caps/session, listener-
                # specific topic namespace (emqx_listeners.erl:232 analog)
                import dataclasses

                chan_cfg = dataclasses.replace(
                    chan_cfg, mountpoint=spec.mountpoint
                )
            if spec.workers > 0 and spec.type == "tcp":
                # multi-process host data plane: the workers own the
                # client port (SO_REUSEPORT); this process only runs the
                # routing core + fabric (transport/workers.py)
                from emqx_tpu.transport.workers import WorkerPool

                pool = WorkerPool(
                    self, spec.bind, spec.port, spec.workers, c
                )
                await pool.start()
                self.worker_pools.append(pool)
                continue
            await self.listeners.start_listener(
                ListenerConfig(
                    name=spec.name,
                    type=spec.type,
                    bind=spec.bind,
                    port=spec.port,
                    max_connections=spec.max_connections,
                    ssl_certfile=spec.ssl_certfile,
                    ssl_keyfile=spec.ssl_keyfile,
                    ssl_cacertfile=spec.ssl_cacertfile,
                    ssl_verify=spec.ssl_verify,
                ),
                chan_cfg,
            )
        if c.bridges:
            for bspec in c.bridges:
                await self._bridge_manager().create(
                    bspec.id, {**bspec.opts, "enable": bspec.enable}
                )
        if c.gateways:
            from emqx_tpu.gateway.registry import GatewayRegistry

            self.gateways = GatewayRegistry(
                self.broker, self.hooks, retainer=self.retainer,
                psk=self.psk,
            )
            _register_builtin_gateways(self.gateways)
            for gspec in c.gateways:
                if gspec.enable:
                    await self.gateways.load(
                        gspec.type, dict(gspec.opts), name=gspec.name
                    )
        if c.dashboard.enable:
            from emqx_tpu.mgmt.api import MgmtApi

            self.mgmt_server = MgmtApi(self)
            await self.mgmt_server.start(c.dashboard.bind, c.dashboard.port)
        self.started_at = time.time()
        self.olp.start()
        if self.statsd is not None:
            self.statsd.start()
        # runtime plugins (emqx_plugins analog): start configured refs.
        # one broken plugin must not abort broker boot — log and continue
        if c.plugins.start:
            pm = self._plugin_manager()
            for ref in c.plugins.start:
                try:
                    pm.start(ref)
                except Exception:
                    logging.getLogger("emqx_tpu").exception(
                        "plugin %s failed to start; continuing boot", ref
                    )
        # telemetry reporter (opt-in)
        from emqx_tpu.observe.telemetry import Telemetry

        import os as _os

        self.telemetry = Telemetry(
            self,
            enable=c.observe.telemetry.enable,
            url=c.observe.telemetry.url,
            interval=c.observe.telemetry.interval,
            uuid_path=(
                _os.path.join(c.durability.data_dir, "telemetry_uuid")
                if c.durability.enable
                else None
            ),
        )
        self.telemetry.start()
        self._tasks = [
            asyncio.ensure_future(self._housekeeping()),
            asyncio.ensure_future(self._sys_heartbeat()),
            asyncio.ensure_future(self._sys_stats()),
        ]

    def _make_config_handler(self, conf_log=None):
        """Runtime config-update pipeline (emqx_config_handler parity):
        per-subtree side-effect handlers with schema validation and
        rollback; see config/handler.py."""
        import dataclasses as _dc

        from emqx_tpu.config.handler import ConfigHandler

        def set_config(cfg):
            self.config = cfg

        h = ConfigHandler(lambda: self.config, set_config, conf_log=conf_log)

        def apply_mqtt(cfg):
            # patch the SHARED caps object in place: every live channel and
            # listener references it, so new limits apply immediately
            for f in _dc.fields(cfg.mqtt):
                setattr(
                    self.channel_config.caps, f.name, getattr(cfg.mqtt, f.name)
                )

        def apply_limiter(cfg):
            self.limiters.reconfigure(cfg.limiter)

        def apply_authz(cfg):
            self.authz.no_match = cfg.authz.no_match
            self.authz.deny_action = cfg.authz.deny_action
            self.authz.set_rules(
                [self._acl_rule(r) for r in cfg.authz.rules]
            )

        def apply_flapping(cfg):
            if self.flapping is not None:
                self.flapping.max_count = cfg.flapping.max_count
                self.flapping.window = cfg.flapping.window_time
                self.flapping.ban_time = cfg.flapping.ban_time

        def apply_log(cfg: AppConfig) -> None:
            from emqx_tpu.observe import logfmt

            logfmt.set_formatter(cfg.log.formatter)
            logfmt.set_level(cfg.log.level)

        h.register("mqtt", apply_mqtt)
        h.register("limiter", apply_limiter)
        h.register("authz", apply_authz)
        h.register("flapping", apply_flapping)
        h.register("log", apply_log)
        return h

    def _plugin_manager(self):
        if self.plugins is None:
            from emqx_tpu.plugins import PluginManager

            self.plugins = PluginManager(
                self, self.config.plugins.install_dir
            )
        return self.plugins

    def _bridge_manager(self):
        if self.bridges is None:
            from emqx_tpu.integration.bridge import BridgeManager

            self.bridges = BridgeManager(self.broker, self.hooks)
        return self.bridges

    def _bridge_output(self, bridge_id: str):
        """Lazy rule output: bridges may be created after the rule
        (config order, or via REST) — resolve at fire time."""
        from emqx_tpu.rules.engine import FunctionOutput

        def fn(row, ctx):
            if self.bridges is not None:
                self.bridges.send_row(bridge_id, row, ctx)

        return FunctionOutput(fn, name=f"bridge:{bridge_id}")

    async def _cluster_join(self, seeds: List[str]) -> None:
        """Dial seeds until one admits us (peers may still be booting)."""
        loop = asyncio.get_running_loop()
        for _attempt in range(120):
            for seed in seeds:
                try:
                    ok = await loop.run_in_executor(
                        None, self.cluster_node.join, seed
                    )
                    if ok:
                        logging.getLogger("emqx_tpu").info(
                            "joined cluster via %s", seed
                        )
                        return
                except Exception:
                    pass
            await asyncio.sleep(0.5)
        logging.getLogger("emqx_tpu").warning(
            "cluster join failed after all retries: %s", seeds
        )

    async def drain(self, cluster_node=None, peer: Optional[str] = None):
        """Rolling-restart drain (the relup analog, r3 verdict item 7;
        reference tooling: scripts/update_appup.escript + node evacuation):
        stop accepting, close live connections (persistent sessions park
        into the CM + WAL checkpoint), and — when this broker is a
        cluster member — hand every parked session to `peer` over the
        sess v2 protocol so the process can exit with zero message loss
        (ClusterNode.drain_to). The caller restarts/replaces the process;
        a restarted single node restores sessions from the WAL."""
        out = {"handed_off": 0}
        for pool in self.worker_pools:
            await pool.stop()
        self.worker_pools.clear()
        await self.listeners.stop_all()
        if self.gateways is not None:
            await self.gateways.unload_all()
            self.gateways = None
        out["detached_sessions"] = self.cm.detached_count()
        if self.session_persistence is not None:
            self.session_persistence.flush(force=True)
        node = cluster_node or getattr(self, "cluster_node", None)
        if node is not None:
            if not peer:
                peers = node.membership.peers()
                peer = peers[0] if peers else None
            if peer:
                # async variant: rpc round-trips off-loop so inbound
                # forwards keep banking mid-drain
                out["handed_off"] = await node.drain_to_async(peer)
                self.broker.cluster = None
                self.cluster_node = None
        self.broker.metrics.inc("node.drained")
        return out

    async def stop(self) -> None:
        if self.broker.ingest is not None:
            await self.broker.ingest.stop()
            self.broker.ingest = None
        if self.broker.retained_feed is not None:
            # unhook the storm feed: replays after stop fall back to the
            # synchronous CPU/device match path
            self.retainer.storm_feed = None
            self.broker.retained_feed = None
        for t in self._tasks:
            t.cancel()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)
        await self.olp.stop()
        if self.statsd is not None:
            await self.statsd.stop()
        if self.mgmt_server is not None:
            await self.mgmt_server.stop()
        if self.telemetry is not None:
            await self.telemetry.stop()
        if self.plugins is not None:
            self.plugins.stop_all()
        if self.gateways is not None:
            await self.gateways.unload_all()
        if self.bridges is not None:
            await self.bridges.close()
        for pool in self.worker_pools:
            await pool.stop()
        self.worker_pools.clear()
        await self.listeners.stop_all()
        if getattr(self, "cluster_node", None) is not None:
            try:
                self.cluster_node.leave()
            except Exception:
                pass
            self.cluster_node = None
        if getattr(self, "cluster_bus", None) is not None:
            self.cluster_bus.stop()
            self.cluster_bus = None
        # final checkpoint AFTER listeners close: connection teardown parks
        # live persistent sessions into cm._detached, so the snapshot
        # includes clients that were still connected at shutdown
        if self.session_persistence is not None:
            self.session_persistence.flush(force=True)
        if self.durable_state is not None:
            self.durable_state.flush()
        if self.sys_mon is not None:
            self.sys_mon.close()
        if self.exhook is not None:
            self.exhook.shutdown()
        # external auth backends hold lazily-created HTTP sessions
        if self.authn is not None:
            for prov in self.authn.providers:
                closer = getattr(prov, "close", None)
                if closer is not None:
                    await closer()
        for src in self.authz.sources:
            closer = getattr(src, "close", None)
            if closer is not None:
                await closer()
        if self.spans is not None:
            self.spans.close()  # flush the OTLP file exporter buffer
        self.trace.close()

    async def _housekeeping(self) -> None:
        import logging

        c = self.config
        last_retainer_sweep = 0.0
        last_session_sweep = 0.0
        last_durability_flush = time.time()
        # mesh.shard.* accounting (scale-out serving): scatter launches
        # diff the segment managers' counters; the lane-fill scan walks
        # the subscriber matrix, so it runs every 30th tick only
        last_shard_launches = 0
        mesh_fill_tick = 0
        while True:
            await asyncio.sleep(1.0)
            try:
                now = time.time()
                # delayed dues + detached-session deadlines are
                # MONOTONIC (clock-step immunity): let them read their
                # own clock instead of passing wall time
                self.delayed.tick()
                self.cm.sweep_expired()
                self.banned.sweep(now)
                if self.flapping is not None:
                    self.flapping.sweep(now)
                if now - last_retainer_sweep >= c.retainer.msg_clear_interval:
                    self.retainer.clear_expired(now)
                    last_retainer_sweep = now
                if self.sys_mon is not None:
                    self.sys_mon.check(now, 1.0)
                if self.os_mon is not None:
                    self.os_mon.check(now)
                if self.vm_mon is not None:
                    self.vm_mon.check(now)
                self.slow_subs.sweep(now)
                self.alarms.sweep(now)
                if self.fallback_watch is not None:
                    self.fallback_watch.check(now)
                if self.slo_watch is not None:
                    self.slo_watch.check(now)
                if self.device_watch is not None:
                    self.device_watch.poll(now)
                # bounded profile captures: auto-disarm past the
                # deadline or the on-disk byte budget (profiler.tick
                # is a no-op while disarmed)
                self.profiler.tick()
                if self.retrace_watch is not None:
                    self.retrace_watch.check(now)
                dev = self.broker._device
                if self.segment_compactor is not None and dev is not None:
                    st = dev.segment_status()
                    m = self.broker.metrics
                    m.gauge_set("router.segment.hot.fill", st["hot_fill"])
                    m.gauge_set(
                        "router.segment.hot.capacity", st["hot_capacity"]
                    )
                    m.gauge_set(
                        "router.segment.tombstones", st["tombstones"]
                    )
                    st_sub = self.broker.subtab.status()
                    if st_sub["mode"] == "sparse":
                        m.gauge_set("router.sparse.bytes", st_sub["bytes"])
                        m.gauge_set(
                            "router.sparse.fill", st_sub["csr_fill"]
                        )
                        m.gauge_set(
                            "router.sparse.tombstones",
                            st_sub["csr_tombstones"],
                        )
                        m.gauge_set(
                            "router.sparse.hot.fill", st_sub["hot_fill"]
                        )
                    rc = self.config.router
                    owners = dev.compaction_owners(
                        hot_entries=rc.compact_hot_entries,
                        tombstone_frac=rc.compact_tombstone_frac,
                    )
                    if self.session_store is not None:
                        # fourth owner on the one compactor: purge acked
                        # (tombstoned) session rows off the critical path
                        owners.append(
                            self.session_store.compaction_owner(
                                tombstone_frac=rc.compact_tombstone_frac
                            )
                        )
                    self.segment_compactor.tick(owners)
                if (
                    dev is not None
                    and self.broker.mesh is not None
                    and hasattr(dev, "shard_status")
                ):
                    m = self.broker.metrics
                    launches = (
                        dev._shape_sync.delta_launches
                        + dev._bits_sync.delta_launches
                        + dev._nfa_sync.delta_launches
                    )
                    if launches > last_shard_launches:
                        m.inc(
                            "mesh.shard.scatter.launches",
                            launches - last_shard_launches,
                        )
                        last_shard_launches = launches
                    if mesh_fill_tick % 30 == 0:
                        st = dev.shard_status()
                        m.gauge_set("mesh.shard.count", st["shards"])
                        m.gauge_set(
                            "mesh.shard.fill",
                            st.get("lane_fill_max", 0.0),
                        )
                    mesh_fill_tick += 1
                if (
                    self.session_store is not None
                    and now - last_session_sweep
                    >= c.session.store_sweep_interval
                ):
                    # arm a retry/expiry sweep to ride the next serving
                    # launch (host fallback scan when idle / non-fusing)
                    dev2 = self.broker._device
                    self.session_store.tick(
                        fused_path=dev2 is not None
                        and getattr(
                            dev2, "supports_session_fusion", False
                        )
                    )
                    last_session_sweep = now
                self.trace.sweep(now)
                self.license.tick(now)
                self.topic_metrics.tick_rates(now)
                if (
                    self.session_persistence is not None
                    and now - last_durability_flush
                    >= c.durability.flush_interval
                ):
                    # non-forced: flush() itself knows when a write is
                    # needed (lifecycle hooks fired or detached queues live)
                    self.session_persistence.flush()
                    if self.durable_state is not None:
                        self.durable_state.flush()
                    last_durability_flush = now
            except asyncio.CancelledError:
                raise
            except Exception:
                # one bad tick must not kill periodic work for the process
                logging.getLogger("emqx_tpu").exception("housekeeping tick failed")

    def _publish_sys(self, stats: dict) -> None:
        import logging

        for topic, payload in stats.items():
            try:
                self.broker.publish(
                    Message(topic=topic, payload=payload.encode(), qos=0)
                )
            except Exception:
                # a raising publish hook must not kill the $SYS loops
                logging.getLogger("emqx_tpu").exception("$SYS publish failed")

    async def _sys_heartbeat(self) -> None:
        """$SYS liveness beat: uptime/datetime at sys_heartbeat_interval
        (reference: emqx_sys.erl heartbeat vs. the slower info messages)."""
        import datetime

        prefix = f"$SYS/brokers/{node_name()}"
        while True:
            self._publish_sys(
                {
                    f"{prefix}/uptime": str(
                        int(time.time() - (self.started_at or time.time()))
                    ),
                    f"{prefix}/datetime": datetime.datetime.now(
                        datetime.timezone.utc
                    ).isoformat(),
                }
            )
            await asyncio.sleep(self.config.sys.sys_heartbeat_interval)

    async def _sys_stats(self) -> None:
        """$SYS broker info/stats topics (reference: emqx_sys.erl:70-95)."""
        from emqx_tpu import __version__

        prefix = f"$SYS/brokers/{node_name()}"
        while True:
            self._publish_sys(
                {
                    f"{prefix}/version": __version__,
                    f"{prefix}/clients/count": str(self.cm.channel_count()),
                    f"{prefix}/subscriptions/count": str(
                        self.broker.subscription_count()
                    ),
                    f"{prefix}/retained/count": str(len(self.retainer)),
                }
            )
            await asyncio.sleep(self.config.sys.sys_msg_interval)
