"""exhook: out-of-process hook extension over gRPC.

Reference: apps/emqx_exhook (SURVEY.md §2.2) — the broker bridges every
hookpoint to a gRPC `HookProvider` sidecar, with per-server timeouts,
fallback actions and per-hook metrics. This is also the designated seam for
attaching external matchers/processors (the TPU sidecar pattern named in
SURVEY.md's north star).
"""
