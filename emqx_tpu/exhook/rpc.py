"""gRPC plumbing for the HookProvider service without grpc_tools codegen.

protoc (no grpc plugin in this toolchain) generates only the message
classes; the service stub and server registration are built here from
grpc-core primitives (`unary_unary` channel callables and
`method_handlers_generic_handler`), which is the same wire contract the
generated code would produce.
"""

from __future__ import annotations

import grpc

from emqx_tpu.exhook import hookprovider_pb2 as pb

# The reference service path — a provider binary built against the
# reference proto (exhook.proto:25) attaches unchanged.
SERVICE = "emqx.exhook.v1.HookProvider"

# rpc name -> (request message class, response message class)
METHODS = {
    "OnProviderLoaded": (pb.ProviderLoadedRequest, pb.LoadedResponse),
    "OnProviderUnloaded": (pb.ProviderUnloadedRequest, pb.EmptySuccess),
    "OnClientConnect": (pb.ClientConnectRequest, pb.EmptySuccess),
    "OnClientConnack": (pb.ClientConnackRequest, pb.EmptySuccess),
    "OnClientConnected": (pb.ClientConnectedRequest, pb.EmptySuccess),
    "OnClientDisconnected": (pb.ClientDisconnectedRequest, pb.EmptySuccess),
    "OnClientAuthenticate": (pb.ClientAuthenticateRequest, pb.ValuedResponse),
    "OnClientAuthorize": (pb.ClientAuthorizeRequest, pb.ValuedResponse),
    "OnClientSubscribe": (pb.ClientSubscribeRequest, pb.EmptySuccess),
    "OnClientUnsubscribe": (pb.ClientUnsubscribeRequest, pb.EmptySuccess),
    "OnSessionCreated": (pb.SessionCreatedRequest, pb.EmptySuccess),
    "OnSessionSubscribed": (pb.SessionSubscribedRequest, pb.EmptySuccess),
    "OnSessionUnsubscribed": (pb.SessionUnsubscribedRequest, pb.EmptySuccess),
    "OnSessionResumed": (pb.SessionResumedRequest, pb.EmptySuccess),
    "OnSessionDiscarded": (pb.SessionDiscardedRequest, pb.EmptySuccess),
    "OnSessionTakenover": (pb.SessionTakenoverRequest, pb.EmptySuccess),
    "OnSessionTerminated": (pb.SessionTerminatedRequest, pb.EmptySuccess),
    "OnMessagePublish": (pb.MessagePublishRequest, pb.ValuedResponse),
    "OnMessageDelivered": (pb.MessageDeliveredRequest, pb.EmptySuccess),
    "OnMessageDropped": (pb.MessageDroppedRequest, pb.EmptySuccess),
    "OnMessageAcked": (pb.MessageAckedRequest, pb.EmptySuccess),
}


class HookProviderStub:
    """Client-side stub (the broker is the gRPC client)."""

    def __init__(self, channel: grpc.Channel):
        for name, (req_cls, resp_cls) in METHODS.items():
            setattr(
                self,
                name,
                channel.unary_unary(
                    f"/{SERVICE}/{name}",
                    request_serializer=req_cls.SerializeToString,
                    response_deserializer=resp_cls.FromString,
                ),
            )


def add_hook_provider_to_server(servicer, server: grpc.Server) -> None:
    """Register a servicer (any object with OnXxx methods) on a grpc
    server. Missing methods default to returning EmptySuccess/CONTINUE."""

    def _default(resp_cls):
        def handler(request, context):
            if resp_cls is pb.ValuedResponse:
                return pb.ValuedResponse(
                    type=pb.ValuedResponse.ResponsedType.CONTINUE
                )
            if resp_cls is pb.LoadedResponse:
                return pb.LoadedResponse()
            return resp_cls()

        return handler

    handlers = {}
    for name, (req_cls, resp_cls) in METHODS.items():
        fn = getattr(servicer, name, None) or _default(resp_cls)
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            fn,
            request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString,
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),)
    )
