"""Helper for implementing a HookProvider sidecar in Python.

The reference ships exhook as protocol-only (providers are user programs);
this helper is the equivalent of its example SDKs: subclass
`HookProviderServicer`, override the OnXxx methods you care about, and
`serve()` it. Also the template for a TPU-side matcher sidecar.
"""

from __future__ import annotations

from concurrent import futures
from typing import List, Optional, Tuple

import grpc

from emqx_tpu.exhook import hookprovider_pb2 as pb
from emqx_tpu.exhook.rpc import add_hook_provider_to_server


class HookProviderServicer:
    """Base class: override the RPCs you need. `hooks` limits which
    hookpoints the broker bridges (None = all)."""

    hooks: Optional[List[Tuple[str, List[str]]]] = None

    def OnProviderLoaded(self, request, context):
        specs = []
        for item in self.hooks or []:
            if isinstance(item, str):
                specs.append(pb.HookSpec(name=item))
            else:
                name, topics = item
                specs.append(pb.HookSpec(name=name, topics=topics))
        return pb.LoadedResponse(hooks=specs)

    # convenience builders for subclasses
    @staticmethod
    def continue_():
        return pb.ValuedResponse(
            type=pb.ValuedResponse.ResponsedType.CONTINUE
        )

    @staticmethod
    def stop_bool(value: bool):
        return pb.ValuedResponse(
            type=pb.ValuedResponse.ResponsedType.STOP_AND_RETURN,
            bool_result=value,
        )

    @staticmethod
    def stop_message(message: pb.Message):
        return pb.ValuedResponse(
            type=pb.ValuedResponse.ResponsedType.STOP_AND_RETURN,
            message=message,
        )


def serve(
    servicer: HookProviderServicer,
    bind: str = "127.0.0.1:0",
    max_workers: int = 8,
) -> Tuple[grpc.Server, int]:
    """Start a HookProvider server; returns (server, bound_port)."""
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    add_hook_provider_to_server(servicer, server)
    port = server.add_insecure_port(bind)
    server.start()
    return server, port
