"""exhook manager: bridges broker hookpoints to gRPC HookProvider sidecars.

Parity with apps/emqx_exhook/src/emqx_exhook_mgr.erl + emqx_exhook_handler.erl
(SURVEY.md §2.2): per-server config (url, timeout, failed_action), hook
registration driven by the provider's OnProviderLoaded response, per-hook
call/error metrics, deny-or-ignore fallback when the sidecar is down.

In the reference each connection is its own Erlang process, so an inline
gRPC call only blocks that one client. Here the broker shares one event
loop, so gRPC never runs on it: every server gets a single worker thread
(ordering-preserving). Lifecycle notifications are enqueued fire-and-forget;
valued hooks (authenticate/authorize/message.publish) are coroutines that
await the worker's result, suspending only the calling connection's task.
A breaker trips after consecutive failures so a dead sidecar costs ~one
timeout, not one timeout per message.
"""

from __future__ import annotations

import asyncio
import logging
import time
import threading
from collections import defaultdict
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

import grpc

from emqx_tpu.broker.hooks import Hooks
from emqx_tpu.broker.message import Message
from emqx_tpu.exhook import hookprovider_pb2 as pb
from emqx_tpu.exhook.rpc import HookProviderStub
from emqx_tpu.observe import faults as _faults
from emqx_tpu.observe.faults import FaultError
from emqx_tpu.ops import topics as T
from emqx_tpu.utils.node import node_name

log = logging.getLogger("emqx_tpu.exhook")

ALL_HOOKS = (
    "client.connect",
    "client.connack",
    "client.connected",
    "client.disconnected",
    "client.authenticate",
    "client.authorize",
    "client.subscribe",
    "client.unsubscribe",
    "session.created",
    "session.subscribed",
    "session.unsubscribed",
    "session.resumed",
    "session.discarded",
    "session.takenover",
    "session.terminated",
    "message.publish",
    "message.delivered",
    "message.dropped",
    "message.acked",
)


def _ci(client_info: Dict, password: str = "") -> pb.ClientInfo:
    return pb.ClientInfo(
        node=node_name(),
        clientid=str(client_info.get("client_id") or ""),
        username=str(client_info.get("username") or ""),
        password=password,
        peerhost=str(client_info.get("peerhost") or ""),
        sockport=int(client_info.get("sockport") or 0),
        protocol=str(client_info.get("protocol") or "mqtt"),
        mountpoint=str(client_info.get("mountpoint") or ""),
        is_superuser=bool(client_info.get("is_superuser", False)),
        anonymous=not client_info.get("username"),
    )


def _conninfo(client_info: Dict) -> pb.ConnInfo:
    return pb.ConnInfo(
        node=node_name(),
        clientid=str(client_info.get("client_id") or ""),
        username=str(client_info.get("username") or ""),
        peerhost=str(client_info.get("peerhost") or ""),
        sockport=int(client_info.get("sockport") or 0),
        proto_name="MQTT",
        proto_ver=str(client_info.get("proto_ver") or ""),
        keepalive=int(client_info.get("keepalive") or 0),
    )


def _msg_build(m: Message) -> pb.Message:
    out = pb.Message(
        node=node_name(),
        id=str(m.mid),
        qos=m.qos,
        topic=m.topic,
        payload=m.payload,
        timestamp=int(m.timestamp * 1000),
    )
    # 'from' is a Python keyword; protobuf exposes the field by name via
    # setattr
    setattr(out, "from", m.from_client)
    if m.from_username:
        out.headers["username"] = str(m.from_username)
    out.headers["protocol"] = "mqtt"
    for k, v in m.headers.items():
        if isinstance(v, bool):
            out.headers[str(k)] = "true" if v else "false"
        elif isinstance(v, (str, int, float)):
            out.headers[str(k)] = str(v)
    return out


def _apply_msg(original: Message, p: pb.Message) -> Message:
    import copy

    m = copy.copy(original)
    m.topic = p.topic
    m.payload = p.payload
    m.qos = p.qos
    m.headers = dict(original.headers)
    for k, v in p.headers.items():
        if k in ("username", "protocol", "peerhost"):
            continue  # readonly mirror headers, not broker state
        if k == "allow_publish":
            # the reference's writable header is string "true"/"false"
            m.headers[k] = v != "false"
        else:
            m.headers[k] = v
    return m


class ExhookServer:
    """One configured sidecar: channel + stub + hook registration state."""

    def __init__(
        self,
        name: str,
        url: str,
        timeout: float = 0.5,
        failed_action: str = "deny",  # deny | ignore
        pool_size: int = 8,
        breaker_threshold: int = 3,
        breaker_cooldown: float = 5.0,
    ):
        if failed_action not in ("deny", "ignore"):
            raise ValueError("failed_action must be deny|ignore")
        self.name = name
        self.url = url
        self.timeout = timeout
        self.failed_action = failed_action
        self.channel = grpc.insecure_channel(url)
        self.stub = HookProviderStub(self.channel)
        self.hooks: Dict[str, List[str]] = {}  # hook -> topic filters
        self.metrics = defaultdict(lambda: {"succeed": 0, "failed": 0})
        self.loaded = False
        # two lanes off the event loop: notifications (1 thread, ordered,
        # fire-and-forget) must not delay latency-sensitive valued calls
        # (auth/authorize/publish), which get pool_size workers — per-
        # connection ordering is already guaranteed by the awaiting task
        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"exhook-{name}-notify"
        )
        self._pool_valued = ThreadPoolExecutor(
            max_workers=max(1, pool_size),
            thread_name_prefix=f"exhook-{name}-valued",
        )
        self._notify_backlog = 0  # guarded-by: _notify_lock (worker thread
        self._notify_lock = threading.Lock()  # decrements, loop increments)
        self._notify_backlog_max = 1000
        # breaker state + per-hook counters mutate from BOTH worker lanes
        # (up to pool_size valued workers run `call` concurrently) and
        # are read on the loop: unlocked `+=` here loses increments, so
        # a flapping sidecar could stay under the trip threshold forever
        # (found by the CX checker / racetrack, PR 8)
        self._state_lock = threading.Lock()
        self._consec_failures = 0  # guarded-by: _state_lock
        self._breaker_threshold = breaker_threshold
        self._breaker_cooldown = breaker_cooldown
        self._broken_until = 0.0  # guarded-by: _state_lock

    def load(self, version: str) -> bool:
        """OnProviderLoaded handshake: learn which hooks to bridge."""
        try:
            resp = self.stub.OnProviderLoaded(
                pb.ProviderLoadedRequest(
                    broker=pb.BrokerInfo(
                        version=version,
                        sysdescr=f"emqx_tpu on {node_name()}",
                        datetime=time.strftime("%Y-%m-%dT%H:%M:%S"),
                    )
                ),
                timeout=self.timeout,
            )
        except grpc.RpcError as e:
            log.warning("exhook %s load failed: %s", self.name, e)
            return False
        self.hooks = {
            h.name: list(h.topics)
            for h in resp.hooks
            if h.name in ALL_HOOKS
        }
        if not self.hooks:
            # empty response = all hooks (reference default registration)
            self.hooks = {h: [] for h in ALL_HOOKS}
        self.loaded = True
        return True

    def unload(self) -> None:
        try:
            self.stub.OnProviderUnloaded(
                pb.ProviderUnloadedRequest(), timeout=self.timeout
            )
        except grpc.RpcError:
            pass
        self.loaded = False
        self._pool.shutdown(wait=False)
        self._pool_valued.shutdown(wait=False)
        self.channel.close()

    def topic_interested(self, hook: str, topic: Optional[str]) -> bool:
        filters = self.hooks.get(hook)
        if filters is None:
            return False
        if not filters or topic is None:
            return True
        return any(T.match(topic, f) for f in filters)

    def _breaker_open(self) -> bool:
        with self._state_lock:
            return time.monotonic() < self._broken_until

    def _mark(self, hook: str, ok: bool, trip: bool = True) -> None:
        """Result accounting + breaker ladder, callable from any lane.

        One lock covers the per-hook counter dicts (defaultdict creation
        and `+=` are read-modify-write) and the consecutive-failure
        counter the breaker trips on. `trip=False` counts a failure
        without advancing the ladder: local rejections (breaker already
        open, backlog drop, pool shut down) say nothing about sidecar
        health — letting them extend `_broken_until` would hold the
        breaker open forever under steady traffic."""
        with self._state_lock:
            m = self.metrics[hook]
            if ok:
                m["succeed"] += 1
                self._consec_failures = 0
            else:
                m["failed"] += 1
                if trip:
                    self._consec_failures += 1
                    if self._consec_failures >= self._breaker_threshold:
                        self._broken_until = (
                            time.monotonic() + self._breaker_cooldown
                        )

    def call(self, method: str, request, hook: str, metadata=None):
        """Blocking gRPC call -> (ok, response|None); metrics + breaker.

        `metadata`: optional gRPC metadata tuples — the span context
        (`traceparent`) rides here so a sidecar can join the broker's
        trace (observe/spans.py; it is ALSO mirrored into the message
        headers by the publish-span head).

        Runs on the server's worker thread (or any non-loop thread); never
        call from the event loop — use `acall`/`notify` there.
        """
        if self._breaker_open():
            self._mark(hook, ok=False, trip=False)
            return False, None
        try:
            # fault site: an injected sidecar failure rides the same
            # failed_action + breaker ladder as a real gRPC error
            _faults.hit("exhook.call")
            resp = getattr(self.stub, method)(
                request, timeout=self.timeout, metadata=metadata
            )
            self._mark(hook, ok=True)
            return True, resp
        except (grpc.RpcError, FaultError) as e:
            self._mark(hook, ok=False)
            log.debug("exhook %s %s failed: %s", self.name, method, e)
            return False, None

    async def acall(self, method: str, request, hook: str, metadata=None):
        """Awaitable `call` on the valued-lane worker; only the caller
        waits. A shut-down pool (unload raced with an in-flight packet)
        counts as a failure so failed_action applies."""
        if self._breaker_open():
            self._mark(hook, ok=False, trip=False)
            return False, None
        loop = asyncio.get_running_loop()
        try:
            return await loop.run_in_executor(
                self._pool_valued, self.call, method, request, hook,
                metadata,
            )
        except RuntimeError:
            self._mark(hook, ok=False, trip=False)
            return False, None

    def _notify_done(self, _fut) -> None:
        with self._notify_lock:
            self._notify_backlog -= 1

    def notify(self, method: str, request, hook: str) -> None:
        """Fire-and-forget: enqueue on the notify worker; drop when shut
        down or when the backlog is deep (a stalled sidecar must not grow
        an unbounded queue of stale notifications)."""
        with self._notify_lock:
            if self._notify_backlog >= self._notify_backlog_max:
                drop = True
            else:
                drop = False
                self._notify_backlog += 1
        if drop:
            self._mark(hook, ok=False, trip=False)
            return
        try:
            fut = self._pool.submit(self.call, method, request, hook)
        except RuntimeError:
            self._notify_done(None)
            return
        fut.add_done_callback(self._notify_done)

    def info(self) -> Dict:
        with self._state_lock:
            mstats = {k: dict(v) for k, v in self.metrics.items()}
        return {
            "name": self.name,
            "url": self.url,
            "loaded": self.loaded,
            "failed_action": self.failed_action,
            "hooks": dict(self.hooks),
            "metrics": mstats,
        }


class ExhookManager:
    def __init__(self, version: str = "0"):
        self.version = version
        self.servers: List[ExhookServer] = []

    def add_server(self, server: ExhookServer) -> bool:
        ok = server.load(self.version)
        self.servers.append(server)
        return ok

    def remove_server(self, name: str) -> bool:
        for s in list(self.servers):
            if s.name == name:
                s.unload()
                self.servers.remove(s)
                return True
        return False

    def shutdown(self) -> None:
        for s in self.servers:
            s.unload()
        self.servers.clear()

    def _servers_for(self, hook: str, topic: Optional[str] = None):
        return [
            s
            for s in self.servers
            if s.loaded and s.topic_interested(hook, topic)
        ]

    # -- hook bridges ------------------------------------------------------
    def attach(self, hooks: Hooks) -> None:
        # lifecycle notifications (fire-and-forget semantics, still sync)
        def notify(hook, method, build):
            def cb(*args):
                for s in self._servers_for(hook):
                    s.notify(method, build(*args), hook)

            hooks.add(hook, cb, tag=f"exhook.{hook}")

        notify(
            "client.connect",
            "OnClientConnect",
            lambda ci, p: pb.ClientConnectRequest(conninfo=_conninfo(ci)),
        )
        notify(
            "client.connack",
            "OnClientConnack",
            lambda ci, rc: pb.ClientConnackRequest(
                conninfo=_conninfo(ci), result_code=str(rc)
            ),
        )
        notify(
            "client.connected",
            "OnClientConnected",
            lambda ci, ch: pb.ClientConnectedRequest(clientinfo=_ci(ci)),
        )
        notify(
            "client.disconnected",
            "OnClientDisconnected",
            lambda ci, reason: pb.ClientDisconnectedRequest(
                clientinfo=_ci(ci), reason=str(reason)
            ),
        )
        notify(
            "session.subscribed",
            "OnSessionSubscribed",
            lambda ci, f, opts, ch=None: pb.SessionSubscribedRequest(
                clientinfo=_ci(ci),
                topic=f,
                subopts=pb.SubOpts(
                    qos=getattr(opts, "qos", 0),
                    rh=getattr(opts, "retain_handling", 0),
                    rap=int(getattr(opts, "retain_as_published", False)),
                    nl=int(getattr(opts, "no_local", False)),
                ),
            ),
        )
        notify(
            "session.unsubscribed",
            "OnSessionUnsubscribed",
            lambda ci, f: pb.SessionUnsubscribedRequest(
                clientinfo=_ci(ci), topic=f
            ),
        )
        for hook, method, req_cls in (
            ("session.created", "OnSessionCreated", pb.SessionCreatedRequest),
            ("session.resumed", "OnSessionResumed", pb.SessionResumedRequest),
            ("session.discarded", "OnSessionDiscarded",
             pb.SessionDiscardedRequest),
            ("session.takenover", "OnSessionTakenover",
             pb.SessionTakenoverRequest),
        ):
            notify(
                hook,
                method,
                lambda cid, _cls=req_cls: _cls(
                    clientinfo=pb.ClientInfo(
                        node=node_name(), clientid=str(cid)
                    )
                ),
            )
        notify(
            "session.terminated",
            "OnSessionTerminated",
            lambda cid, reason: pb.SessionTerminatedRequest(
                clientinfo=pb.ClientInfo(
                    node=node_name(), clientid=str(cid)
                ),
                reason=str(reason),
            ),
        )
        notify(
            "message.delivered",
            "OnMessageDelivered",
            lambda ci, m: pb.MessageDeliveredRequest(
                clientinfo=_ci(ci), message=_msg_build(m)
            ),
        )
        notify(
            "message.dropped",
            "OnMessageDropped",
            lambda m, reason: pb.MessageDroppedRequest(
                message=_msg_build(m), reason=str(reason)
            ),
        )

        def acked_cb(ci, msg_or_pid):
            if not isinstance(msg_or_pid, Message):
                return
            for s in self._servers_for("message.acked", msg_or_pid.topic):
                s.notify(
                    "OnMessageAcked",
                    pb.MessageAckedRequest(
                        clientinfo=_ci(ci), message=_msg_build(msg_or_pid)
                    ),
                    "message.acked",
                )

        hooks.add("message.acked", acked_cb, tag="exhook.message.acked")

        # valued hooks: authenticate / authorize / message.publish
        hooks.add(
            "client.authenticate",
            self._on_authenticate,
            priority=-100,  # after in-process auth chain
            tag="exhook.client.authenticate",
        )
        hooks.add(
            "client.authorize",
            self._on_authorize,
            priority=-100,
            tag="exhook.client.authorize",
        )
        hooks.add(
            "message.publish",
            self._on_message_publish,
            priority=-100,  # after rewrite/rules so sidecar sees final form
            tag="exhook.message.publish",
        )

        def subscribe_cb(ci, filters):
            # fold contract: acc is the filter list; exhook only observes
            for s in self._servers_for("client.subscribe"):
                s.notify(
                    "OnClientSubscribe",
                    pb.ClientSubscribeRequest(
                        clientinfo=_ci(ci),
                        topic_filters=[
                            pb.TopicFilter(
                                name=f, qos=getattr(o, "qos", 0)
                            )
                            for f, o in filters
                        ],
                    ),
                    "client.subscribe",
                )
            return None

        hooks.add("client.subscribe", subscribe_cb, tag="exhook.client.subscribe")

        def unsubscribe_cb(ci, filters):
            for s in self._servers_for("client.unsubscribe"):
                s.notify(
                    "OnClientUnsubscribe",
                    pb.ClientUnsubscribeRequest(
                        clientinfo=_ci(ci),
                        topic_filters=[
                            pb.TopicFilter(name=f) for f in filters
                        ],
                    ),
                    "client.unsubscribe",
                )
            return None

        hooks.add(
            "client.unsubscribe", unsubscribe_cb,
            tag="exhook.client.unsubscribe",
        )

    # fold: (ci, credentials), acc None|{"result":...}; coroutine -> only
    # runs on the async channel path (arun_fold), never blocks the loop
    async def _on_authenticate(self, ci, credentials, acc):
        for s in self._servers_for("client.authenticate"):
            pw = credentials.get("password") or b""
            if isinstance(pw, bytes):
                pw = pw.decode("utf-8", "replace")
            chain_ok = not (
                isinstance(acc, dict) and acc.get("result") == "deny"
            )
            ok, resp = await s.acall(
                "OnClientAuthenticate",
                pb.ClientAuthenticateRequest(
                    clientinfo=_ci(ci, password=pw), result=chain_ok
                ),
                "client.authenticate",
            )
            if not ok:
                if s.failed_action == "deny":
                    return ("stop", {"result": "deny"})
                continue
            rt = pb.ValuedResponse.ResponsedType
            if resp.type == rt.IGNORE:
                continue
            if resp.WhichOneof("value") == "bool_result":
                verdict = (
                    {"result": "allow"}
                    if resp.bool_result
                    else {"result": "deny"}
                )
                if resp.type == rt.STOP_AND_RETURN:
                    return ("stop", verdict)
                acc = verdict  # CONTINUE: use the value, keep folding
        return ("ok", acc)

    # fold: (ci, action, topic), acc "allow"/"deny"/"disconnect"
    async def _on_authorize(self, ci, action, topic, acc):
        for s in self._servers_for("client.authorize", topic):
            req_type = (
                pb.ClientAuthorizeRequest.AuthorizeReqType.SUBSCRIBE
                if str(action) == "subscribe"
                else pb.ClientAuthorizeRequest.AuthorizeReqType.PUBLISH
            )
            ok, resp = await s.acall(
                "OnClientAuthorize",
                pb.ClientAuthorizeRequest(
                    clientinfo=_ci(ci),
                    type=req_type,
                    topic=topic,
                    result=(acc == "allow"),
                ),
                "client.authorize",
            )
            if not ok:
                if s.failed_action == "deny":
                    return ("stop", "deny")
                continue
            rt = pb.ValuedResponse.ResponsedType
            if resp.type == rt.IGNORE:
                continue
            if resp.WhichOneof("value") == "bool_result":
                verdict = "allow" if resp.bool_result else "deny"
                if resp.type == rt.STOP_AND_RETURN:
                    return ("stop", verdict)
                acc = verdict  # CONTINUE: use the value, keep folding
        return ("ok", acc)

    # fold: (), acc Message. Coroutine: fires for client-originated
    # publishes (Broker.apublish via the channel); internally generated
    # sync publishes (rules republish, $delayed flush, $SYS) skip exhook,
    # which also rules out sidecar-induced republish loops.
    async def _on_message_publish(self, acc):
        m = acc
        if m is None or m.is_sys():
            return None
        # propagate the span context as gRPC metadata so a sidecar's own
        # tracer can join the broker trace (the header string also rides
        # inside pb.Message.headers via _msg_build)
        ctx = m.headers.get("traceparent")
        md = (("traceparent", ctx),) if isinstance(ctx, str) else None
        for s in self._servers_for("message.publish", m.topic):
            ok, resp = await s.acall(
                "OnMessagePublish",
                pb.MessagePublishRequest(message=_msg_build(m)),
                "message.publish",
                metadata=md,
            )
            if not ok:
                if s.failed_action == "deny":
                    import copy

                    m2 = copy.copy(m)
                    m2.headers = dict(m.headers)
                    m2.headers["allow_publish"] = False
                    return ("stop", m2)
                continue
            rt = pb.ValuedResponse.ResponsedType
            if resp.type == rt.IGNORE:
                continue
            if resp.WhichOneof("value") == "message":
                m = _apply_msg(m, resp.message)
                if resp.type == rt.STOP_AND_RETURN:
                    # stop the whole message.publish chain, not just exhook
                    return ("stop", m)
        return ("ok", m)

    def info(self) -> List[Dict]:
        return [s.info() for s in self.servers]
