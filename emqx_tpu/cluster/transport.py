"""Cluster message transport with keyed ordered channels.

Reference analog: gen_rpc's multi-channel TCP — the data plane picks a
stable channel per topic so per-topic message order is preserved across
nodes while unrelated topics flow in parallel (emqx_rpc.erl:66-80,
`emqx_broker.erl:278-293` forwards keyed by topic).

`LocalBus` is the in-process implementation used by the multi-node test
harness (the analog of the reference's slave-node CT setup,
emqx_router_helper_SUITE.erl:61) and by single-host multi-worker runs.
A TCP implementation can drop in behind the same interface; the RPC and
replication layers only see `send(to_node, channel_key, payload)`.

Delivery model: per (src, dst, channel) FIFO. A partitioned/stopped node
raises NodeUnreachable on send, mirroring gen_rpc's {badtcp,...} errors.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, Optional, Tuple

Handler = Callable[[str, object], Optional[object]]  # (from_node, payload)


class NodeUnreachable(Exception):
    pass


class LocalBus:
    """In-process cluster fabric: registry of node inboxes + partitions."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._handlers: Dict[str, Handler] = {}
        # simulated partitions: set of (a, b) unordered pairs that cannot talk
        self._cut: set[Tuple[str, str]] = set()

    def attach(self, node: str, handler: Handler) -> None:
        with self._lock:
            self._handlers[node] = handler

    def detach(self, node: str) -> None:
        with self._lock:
            self._handlers.pop(node, None)

    def nodes(self) -> list[str]:
        with self._lock:
            return sorted(self._handlers)

    # -- fault injection (test nemesis; reference: docker node kill in FVT) --
    def partition(self, a: str, b: str) -> None:
        with self._lock:
            self._cut.add((min(a, b), max(a, b)))

    def heal(self, a: str, b: str) -> None:
        with self._lock:
            self._cut.discard((min(a, b), max(a, b)))

    def reachable(self, a: str, b: str) -> bool:
        with self._lock:
            return (
                b in self._handlers and (min(a, b), max(a, b)) not in self._cut
            )

    # -- send paths --------------------------------------------------------
    def send(self, src: str, dst: str, payload: object) -> object:
        """Synchronous request/response (gen_rpc call). Returns handler result."""
        with self._lock:
            handler = self._handlers.get(dst)
            cut = (min(src, dst), max(src, dst)) in self._cut
        if handler is None or cut:
            raise NodeUnreachable(f"{src} -> {dst}")
        return handler(src, payload)

    def cast(self, src: str, dst: str, payload: object) -> bool:
        """Fire-and-forget (gen_rpc cast): delivery not guaranteed on cut."""
        try:
            self.send(src, dst, payload)
            return True
        except NodeUnreachable:
            return False


class ChannelPool:
    """Stable key→channel mapping preserving per-key FIFO order.

    gen_rpc parity: the reference hashes the topic to pick one of N TCP
    channels so one topic's forwards never reorder (emqx_rpc.erl:66-80).
    In-process the bus is already synchronous, so this just records the
    channel choice for observability and future TCP transport use.
    """

    def __init__(self, n_channels: int = 8) -> None:
        self.n_channels = n_channels
        self._sent: Dict[int, int] = {}

    def pick(self, key: str) -> int:
        ch = hash(key) % self.n_channels
        self._sent[ch] = self._sent.get(ch, 0) + 1
        return ch

    def stats(self) -> Dict[int, int]:
        return dict(self._sent)


class AsyncSender:
    """Background thread draining an ordered queue per destination node.

    Implements the async forward mode ([rpc, mode] = async,
    emqx_broker.erl:283-288): callers enqueue and return immediately;
    per-destination order is preserved by a single drain thread.
    """

    def __init__(self, bus: LocalBus, src: str) -> None:
        self._bus = bus
        self._src = src
        self._queues: Dict[str, queue.Queue] = {}
        self._threads: Dict[str, threading.Thread] = {}
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self.dropped = 0

    def enqueue(self, dst: str, payload: object) -> None:
        with self._lock:
            q = self._queues.get(dst)
            if q is None:
                q = self._queues[dst] = queue.Queue()
                t = threading.Thread(
                    target=self._drain, args=(dst, q), daemon=True
                )
                self._threads[dst] = t
                t.start()
        q.put(payload)

    def _drain(self, dst: str, q: "queue.Queue") -> None:
        while not self._stop.is_set():
            try:
                payload = q.get(timeout=0.1)
            except queue.Empty:
                continue
            if not self._bus.cast(self._src, dst, payload):
                self.dropped += 1
            q.task_done()

    def flush(self, timeout: float = 5.0) -> None:
        with self._lock:
            qs = list(self._queues.values())
        for q in qs:
            q.join()

    def stop(self) -> None:
        self._stop.set()
