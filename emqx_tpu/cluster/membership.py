"""Cluster membership: join/leave/heartbeat + nodedown notifications.

Reference analog: ekka — autocluster discovery, membership gossip, and
`ekka:monitor(membership)` subscriptions that the router helper uses to
purge a dead node's routes (emqx_router_helper.erl:96,135-148) and the
machine boot uses for autocluster (emqx_machine_boot.erl:46-51).

Failure detection here is heartbeat-deadline based (the BEAM uses
distribution-link breaks); the test nemesis advances a logical clock to
force timeouts deterministically, mirroring snabbkaffe-style scheduling
control rather than wall-clock sleeps.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from emqx_tpu.cluster.transport import LocalBus

MembershipCallback = Callable[[str, str], None]  # (event, node)

HEARTBEAT_INTERVAL = 1.0
FAILURE_TIMEOUT = 3.0


class Membership:
    """One node's view of the cluster, with pluggable clock for tests."""

    def __init__(
        self,
        node: str,
        bus: LocalBus,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        self.node = node
        self._bus = bus
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._last_seen: Dict[str, float] = {}  # guarded-by: _lock
        self._alive: Dict[str, bool] = {node: True}  # guarded-by: _lock
        self._callbacks: List[MembershipCallback] = []

    # -- ekka:monitor(membership) parity ----------------------------------
    def monitor(self, callback: MembershipCallback) -> None:
        self._callbacks.append(callback)

    def _emit(self, event: str, node: str) -> None:
        for cb in list(self._callbacks):
            cb(event, node)

    # -- cluster ops -------------------------------------------------------
    def join(self, seed: str) -> bool:
        """Join the cluster known to `seed` (ekka:join parity)."""
        try:
            peers = self._bus.send(
                self.node, seed, ("membership", "join", self.node)
            )
        except Exception:
            return False
        now = self._clock()
        with self._lock:
            for p in peers:
                if p != self.node and not self._alive.get(p):
                    self._alive[p] = True
                    self._last_seen[p] = now
        for p in peers:
            if p != self.node:
                self._emit("node_up", p)
        return True

    def handle(self, from_node: str, msg) -> object:
        kind = msg[1]
        now = self._clock()
        if kind == "join":
            joiner = msg[2]
            newly = False
            with self._lock:
                if not self._alive.get(joiner):
                    self._alive[joiner] = True
                    newly = True
                self._last_seen[joiner] = now
                view = [n for n, up in self._alive.items() if up]
            if newly:
                self._emit("node_up", joiner)
                # gossip the join to the rest of the cluster
                for p in view:
                    if p not in (self.node, joiner):
                        self._bus.cast(
                            self.node, p, ("membership", "join", joiner)
                        )
            return view
        if kind in ("heartbeat", "heartbeat_ack"):
            with self._lock:
                came_back = not self._alive.get(from_node)
                self._alive[from_node] = True
                self._last_seen[from_node] = now
            if came_back:
                self._emit("node_up", from_node)
            if kind == "heartbeat":
                # receipt-confirmed liveness: the sender learns we are
                # alive from this ack ARRIVING, never from its own send
                # buffer accepting bytes (see heartbeat() below)
                self._bus.cast(
                    self.node, from_node, ("membership", "heartbeat_ack")
                )
            return True
        if kind == "leave":
            with self._lock:
                was_up = self._alive.pop(from_node, False)
                self._last_seen.pop(from_node, None)
            if was_up:
                self._emit("node_down", from_node)
            return True
        return None

    def leave(self) -> None:
        """Graceful leave: notify peers (ekka:leave parity)."""
        for p in self.peers():
            self._bus.cast(self.node, p, ("membership", "leave"))

    def heartbeat(self) -> None:
        """Send one heartbeat round + expire dead peers. Called on a timer.

        `_last_seen` refreshes ONLY when the peer's ack (or any inbound
        membership traffic) arrives — never on the outbound cast
        "succeeding". Over TCP a `sendall` to a freshly-killed peer
        happily buffers in the kernel (the RST comes later), so
        send-side success is evidence about OUR socket, not the peer;
        trusting it kept kill -9'd nodes alive past FAILURE_TIMEOUT
        whenever the connection reader hadn't yet noticed the close
        (the cluster-proc flake this line exists to pin)."""
        for p in self.peers():
            self._bus.cast(self.node, p, ("membership", "heartbeat"))
        self.expire()

    def expire(self) -> None:
        now = self._clock()
        downs = []
        with self._lock:
            for p, seen in list(self._last_seen.items()):
                if self._alive.get(p) and now - seen > FAILURE_TIMEOUT:
                    self._alive[p] = False
                    downs.append(p)
        for p in downs:
            self._emit("node_down", p)

    # -- views -------------------------------------------------------------
    def peers(self) -> List[str]:
        with self._lock:
            return sorted(
                n for n, up in self._alive.items() if up and n != self.node
            )

    def running_nodes(self) -> List[str]:
        with self._lock:
            return sorted(n for n, up in self._alive.items() if up)

    def is_alive(self, node: str) -> bool:
        with self._lock:
            return bool(self._alive.get(node))
