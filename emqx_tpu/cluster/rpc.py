"""Versioned cluster RPC: BPAPI proto discipline over the transport.

Reference analog: every cross-node call in EMQX goes through frozen
`*_proto_vN` modules so rolling upgrades can negotiate the highest version
both sides support (apps/emqx/src/bpapi/README.md:6-50,
emqx_bpapi:supported_version). `emqx_rpc:call/cast/multicall`
(emqx_rpc.erl:22-30) is the thin wrapper underneath.

Here a proto is registered as (api_name, version) -> {method: handler}.
Callers go through `Rpc.call(node, api, method, *args)`; the dispatcher
picks the highest version the callee announced. Methods are explicit and
frozen per version — adding behavior means adding a new version, never
mutating an old one (the static-check discipline the reference enforces
with BPAPI snapshots becomes a runtime assertion here; see
tests/test_cluster.py for the immutability test).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from emqx_tpu.cluster.transport import AsyncSender, ChannelPool, LocalBus, NodeUnreachable


class RpcError(Exception):
    pass


class BpapiRegistry:
    """(api, version) -> {method: handler}; frozen after announce."""

    def __init__(self) -> None:
        self._protos: Dict[Tuple[str, int], Dict[str, Callable]] = {}
        self._frozen: set[Tuple[str, int]] = set()

    def register(
        self, api: str, version: int, methods: Dict[str, Callable]
    ) -> None:
        key = (api, version)
        if key in self._frozen:
            raise RpcError(f"BPAPI {api} v{version} is frozen; bump the version")
        self._protos[key] = dict(methods)
        self._frozen.add(key)

    def versions(self, api: str) -> List[int]:
        return sorted(v for (a, v) in self._protos if a == api)

    def lookup(self, api: str, version: int, method: str) -> Callable:
        proto = self._protos.get((api, version))
        if proto is None or method not in proto:
            raise RpcError(f"unknown {api} v{version}.{method}")
        return proto[method]

    def announce(self) -> Dict[str, List[int]]:
        out: Dict[str, List[int]] = {}
        for (a, v) in self._protos:
            out.setdefault(a, []).append(v)
        return {a: sorted(vs) for a, vs in out.items()}


class Rpc:
    """Per-node RPC endpoint: sync call, async cast, multicall."""

    def __init__(self, node: str, bus: LocalBus) -> None:
        self.node = node
        self._bus = bus
        self.registry = BpapiRegistry()
        self._peer_versions: Dict[str, Dict[str, List[int]]] = {}
        self._channels = ChannelPool()
        self._sender = AsyncSender(bus, node)
        self._lock = threading.Lock()

    # -- version negotiation (emqx_bpapi:supported_version parity) ---------
    def supported_version(self, peer: str, api: str) -> int:
        with self._lock:
            known = self._peer_versions.get(peer)
        if known is None:
            try:
                known = self._bus.send(self.node, peer, ("rpc", "announce"))
            except NodeUnreachable as e:
                raise RpcError(str(e)) from e
            with self._lock:
                self._peer_versions[peer] = known
        mine = set(self.registry.versions(api))
        theirs = set(known.get(api, ()))
        common = mine & theirs
        if not common:
            raise RpcError(f"no common version for {api} with {peer}")
        return max(common)

    def forget_peer(self, peer: str) -> None:
        with self._lock:
            self._peer_versions.pop(peer, None)

    # -- wire handler ------------------------------------------------------
    def handle(self, from_node: str, msg) -> object:
        kind = msg[1]
        if kind == "announce":
            return self.registry.announce()
        if kind == "call":
            _, _, api, version, method, args = msg
            handler = self.registry.lookup(api, version, method)
            return ("ok", handler(*args))
        return None

    # -- caller side (emqx_rpc.erl:22-30 parity) ---------------------------
    def call(self, peer: str, api: str, method: str, *args) -> Any:
        if peer == self.node:
            v = max(self.registry.versions(api))
            return self.registry.lookup(api, v, method)(*args)
        v = self.supported_version(peer, api)
        try:
            r = self._bus.send(
                self.node, peer, ("rpc", "call", api, v, method, args)
            )
        except NodeUnreachable as e:
            raise RpcError(str(e)) from e
        if not (isinstance(r, tuple) and r[0] == "ok"):
            raise RpcError(f"badrpc from {peer}: {r!r}")
        return r[1]

    def cast(self, peer: str, api: str, method: str, *args, key: str = "") -> None:
        """Async, per-key ordered (gen_rpc keyed channel semantics)."""
        if peer == self.node:
            v = max(self.registry.versions(api))
            self.registry.lookup(api, v, method)(*args)
            return
        try:
            v = self.supported_version(peer, api)
        except RpcError:
            return  # unreachable peer: cast is fire-and-forget
        self._channels.pick(key or method)
        self._sender.enqueue(peer, ("rpc", "call", api, v, method, args))

    def multicall(
        self, peers: List[str], api: str, method: str, *args
    ) -> Dict[str, Any]:
        """Call every peer; collect per-node results or error strings."""
        out: Dict[str, Any] = {}
        for p in peers:
            try:
                out[p] = self.call(p, api, method, *args)
            except RpcError as e:
                out[p] = ("badrpc", str(e))
        return out

    def flush(self) -> None:
        self._sender.flush()

    def stop(self) -> None:
        self._sender.stop()
