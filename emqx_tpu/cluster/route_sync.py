"""Replicated cluster route table: topic filter → set of nodes.

Reference analog: the mria-replicated `emqx_route` bag table plus the
replicated trie (emqx_router.erl:75-84,111-125). Every node holds the FULL
cluster filter set (that is what lets publish route locally without a
network hop); the subscriber tables stay node-local.

Consistency split (mria parity, emqx_router.erl:111-125):
- plain-topic routes: dirty async replication (`emqx_router_utils`
  insert_direct_route) — eventual, per-filter ordered;
- wildcard routes: "transactional" — the writer waits for every reachable
  peer to ack before returning, because a half-replicated trie edge breaks
  matching (maybe_trans, emqx_router.erl:118-121).

TPU note: the internal `Router` compiles this cluster-wide filter set into
the NFA tables, so one device kernel yields dests for a whole batch of
publishes; bitmaps of *local* subscribers are applied on each owner node.
"""

from __future__ import annotations

import threading
import zlib
from typing import Dict, List, Optional, Set, Tuple

from emqx_tpu.broker.router import Router


class ShardOwnership:
    """Cluster-wide mesh-slice ownership (scale-out serving,
    docs/scale_out.md).

    Each serving node runs its own ('dp','tp') device mesh and owns a
    SLICE of the global subscriber-lane space — shard ids are
    ``s{index}/{total}`` plus the node's local mesh shape, advertised
    over the ``shard`` BPAPI proto on join (the mria-replicated
    ownership-table analog). The map answers two questions on the
    publish path:

    - which node currently serves a shard (``owner``), and
    - where publishes bound for a DEAD owner should go instead
      (``successor_node``): on node_down the dead node's home shards
      re-own onto survivors by rendezvous hash — every replica computes
      the same assignment with zero coordination RPCs — so the forward
      path reroutes to the adopting slice instead of stalling behind the
      dead peer's send deadline (the degrade ladder's cluster breakers
      already fail those sends fast; this gives them a live target).
      A returning owner re-advertises and reclaims its home shards.
    """

    def __init__(self, node: str, metrics=None) -> None:
        self.node = node
        self.metrics = metrics
        self._lock = threading.Lock()
        # shard id -> current owner node          guarded-by: _lock
        self._owner: Dict[str, str] = {}
        # node -> (home shard ids, mesh shape)    guarded-by: _lock
        self._home: Dict[str, Tuple[List[str], Tuple[int, int]]] = {}
        self._local: List[str] = []  # guarded-by: _lock

    @staticmethod
    def slice_ids(index: int, total: int) -> List[str]:
        """Shard ids of cluster slice `index` of `total` (one global
        slice per node today; the id scheme leaves room for splitting a
        slice finer than a node later)."""
        if not (0 <= index < total):
            raise ValueError(f"shard slice {index}/{total} out of range")
        return [f"s{index}/{total}"]

    # -- advertisement (BPAPI `shard` proto) -------------------------------
    def advertise_local(
        self, mesh_shape: Tuple[int, int], index: int, total: int
    ) -> List[str]:
        shards = self.slice_ids(index, total)
        self.advertise(self.node, shards, tuple(mesh_shape))
        with self._lock:
            self._local = list(shards)
        return shards

    def advertise(
        self, node: str, shards: List[str],
        mesh_shape: Tuple[int, int] = (0, 0),
    ) -> None:
        """A node announcing its home slice (join or node_up return):
        home shards return to their advertiser — reclaim is part of the
        rebalance ladder, not a special case."""
        with self._lock:
            self._home[node] = (list(shards), tuple(mesh_shape))
            for s in shards:
                self._owner[s] = node

    def local_shards(self) -> List[str]:
        with self._lock:
            return list(self._local)

    def label(self) -> str:
        """Span/metric label for this node's slice ("local" when no
        slice is advertised — a standalone mesh broker)."""
        with self._lock:
            if not self._local:
                return "local"
            shape = self._home.get(self.node, ((), (0, 0)))[1]
            lbl = "+".join(self._local)
            if shape != (0, 0):
                lbl += f"@dp{shape[0]}tp{shape[1]}"
            return lbl

    # -- reads -------------------------------------------------------------
    def owner(self, shard: str) -> Optional[str]:
        with self._lock:
            return self._owner.get(shard)

    def shard_count(self) -> int:
        with self._lock:
            return len(self._owner)

    def successor_node(self, dead: str) -> Optional[str]:
        """The node serving `dead`'s FIRST home shard now (None while
        the map has no better answer than the dead node itself)."""
        with self._lock:
            home = self._home.get(dead, ((), None))[0]
            for s in home:
                cur = self._owner.get(s)
                if cur is not None and cur != dead:
                    return cur
        return None

    # -- rebalance ladder --------------------------------------------------
    def reown(self, dead: str, survivors: List[str]) -> List[Tuple[str, str]]:
        """Reassign every shard `dead` owned onto `survivors` by
        rendezvous hash (deterministic: all replicas agree without a
        coordination round). Returns [(shard, new_owner)] moves; counts
        each into `mesh.shard.rebalance`."""
        cands = sorted(n for n in survivors if n != dead)
        moves: List[Tuple[str, str]] = []
        with self._lock:
            for s, cur in list(self._owner.items()):
                if cur != dead:
                    continue
                if not cands:
                    del self._owner[s]  # no survivor: orphan, not a lie
                    continue
                new = max(
                    cands,
                    key=lambda n: zlib.crc32(f"{s}|{n}".encode()),
                )
                self._owner[s] = new
                moves.append((s, new))
        if self.metrics is not None:
            for _ in moves:
                self.metrics.inc("mesh.shard.rebalance")
        return moves

    # -- bootstrap ---------------------------------------------------------
    def dump(self) -> List[tuple]:
        with self._lock:
            return [
                (n, list(shards), list(shape))
                for n, (shards, shape) in self._home.items()
            ]

    def load(self, dump: List[tuple]) -> None:
        for n, shards, shape in dump:
            self.advertise(n, list(shards), tuple(shape))


class ClusterRouteTable:
    """One node's replica of the global route table."""

    def __init__(self, node: str, router: Optional[Router] = None) -> None:
        self.node = node
        self._router = router or Router(enable_tpu=False)
        # filter -> nodes having >=1 local subscriber on it
        self._dests: Dict[str, Set[str]] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    # -- replica writes (applied locally AND via RPC from peers) ----------
    def add_route(self, filter_: str, node: str) -> None:
        with self._lock:
            dests = self._dests.get(filter_)
            if dests is None:
                dests = self._dests[filter_] = set()
                self._router.add_route(filter_)
            dests.add(node)

    def delete_route(self, filter_: str, node: str) -> None:
        with self._lock:
            dests = self._dests.get(filter_)
            if dests is None:
                return
            dests.discard(node)
            if not dests:
                del self._dests[filter_]
                self._router.delete_route(filter_)

    def cleanup_node(self, node: str) -> int:
        """Purge all routes owned by a dead node (emqx_router_helper:135-148).

        The reference serializes this under a global lock so only one
        surviving node runs the mnesia transaction; here every node purges
        its own replica, which is the equivalent end state.
        """
        removed = 0
        with self._lock:
            for filter_ in list(self._dests):
                dests = self._dests[filter_]
                if node in dests:
                    dests.discard(node)
                    removed += 1
                    if not dests:
                        del self._dests[filter_]
                        self._router.delete_route(filter_)
        return removed

    # -- bootstrap (mria replica catch-up on join) -------------------------
    def dump(self) -> List[tuple]:
        with self._lock:
            return [(f, sorted(ns)) for f, ns in self._dests.items()]

    def load(self, dump: List[tuple]) -> None:
        for filter_, nodes in dump:
            for n in nodes:
                self.add_route(filter_, n)

    # -- reads -------------------------------------------------------------
    def match_dests(self, topic: str) -> Dict[str, List[str]]:
        """topic -> {node: [matched filters]} (emqx_router:match_routes)."""
        out: Dict[str, List[str]] = {}
        with self._lock:
            for f in self._router.match(topic):
                for n in self._dests.get(f, ()):
                    out.setdefault(n, []).append(f)
        return out

    def match_dests_batch(
        self, topics: List[str]
    ) -> List[Dict[str, List[str]]]:
        """Batch form: one TPU/NFA match for all topics, then dest joins."""
        with self._lock:
            matches = self._router.match_batch(topics)
            out = []
            for filters in matches:
                d: Dict[str, List[str]] = {}
                for f in filters:
                    for n in self._dests.get(f, ()):
                        d.setdefault(n, []).append(f)
                out.append(d)
        return out

    def has_route(self, filter_: str) -> bool:
        with self._lock:
            return filter_ in self._dests

    def routes(self) -> List[tuple]:
        with self._lock:
            return [
                (f, n) for f, ns in self._dests.items() for n in sorted(ns)
            ]

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "routes.count": sum(len(ns) for ns in self._dests.values()),
                "topics.count": len(self._dests),
            }
