"""Replicated cluster route table: topic filter → set of nodes.

Reference analog: the mria-replicated `emqx_route` bag table plus the
replicated trie (emqx_router.erl:75-84,111-125). Every node holds the FULL
cluster filter set (that is what lets publish route locally without a
network hop); the subscriber tables stay node-local.

Consistency split (mria parity, emqx_router.erl:111-125):
- plain-topic routes: dirty async replication (`emqx_router_utils`
  insert_direct_route) — eventual, per-filter ordered;
- wildcard routes: "transactional" — the writer waits for every reachable
  peer to ack before returning, because a half-replicated trie edge breaks
  matching (maybe_trans, emqx_router.erl:118-121).

TPU note: the internal `Router` compiles this cluster-wide filter set into
the NFA tables, so one device kernel yields dests for a whole batch of
publishes; bitmaps of *local* subscribers are applied on each owner node.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set

from emqx_tpu.broker.router import Router


class ClusterRouteTable:
    """One node's replica of the global route table."""

    def __init__(self, node: str, router: Optional[Router] = None) -> None:
        self.node = node
        self._router = router or Router(enable_tpu=False)
        # filter -> nodes having >=1 local subscriber on it
        self._dests: Dict[str, Set[str]] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    # -- replica writes (applied locally AND via RPC from peers) ----------
    def add_route(self, filter_: str, node: str) -> None:
        with self._lock:
            dests = self._dests.get(filter_)
            if dests is None:
                dests = self._dests[filter_] = set()
                self._router.add_route(filter_)
            dests.add(node)

    def delete_route(self, filter_: str, node: str) -> None:
        with self._lock:
            dests = self._dests.get(filter_)
            if dests is None:
                return
            dests.discard(node)
            if not dests:
                del self._dests[filter_]
                self._router.delete_route(filter_)

    def cleanup_node(self, node: str) -> int:
        """Purge all routes owned by a dead node (emqx_router_helper:135-148).

        The reference serializes this under a global lock so only one
        surviving node runs the mnesia transaction; here every node purges
        its own replica, which is the equivalent end state.
        """
        removed = 0
        with self._lock:
            for filter_ in list(self._dests):
                dests = self._dests[filter_]
                if node in dests:
                    dests.discard(node)
                    removed += 1
                    if not dests:
                        del self._dests[filter_]
                        self._router.delete_route(filter_)
        return removed

    # -- bootstrap (mria replica catch-up on join) -------------------------
    def dump(self) -> List[tuple]:
        with self._lock:
            return [(f, sorted(ns)) for f, ns in self._dests.items()]

    def load(self, dump: List[tuple]) -> None:
        for filter_, nodes in dump:
            for n in nodes:
                self.add_route(filter_, n)

    # -- reads -------------------------------------------------------------
    def match_dests(self, topic: str) -> Dict[str, List[str]]:
        """topic -> {node: [matched filters]} (emqx_router:match_routes)."""
        out: Dict[str, List[str]] = {}
        with self._lock:
            for f in self._router.match(topic):
                for n in self._dests.get(f, ()):
                    out.setdefault(n, []).append(f)
        return out

    def match_dests_batch(
        self, topics: List[str]
    ) -> List[Dict[str, List[str]]]:
        """Batch form: one TPU/NFA match for all topics, then dest joins."""
        with self._lock:
            matches = self._router.match_batch(topics)
            out = []
            for filters in matches:
                d: Dict[str, List[str]] = {}
                for f in filters:
                    for n in self._dests.get(f, ()):
                        d.setdefault(n, []).append(f)
                out.append(d)
        return out

    def has_route(self, filter_: str) -> bool:
        with self._lock:
            return filter_ in self._dests

    def routes(self) -> List[tuple]:
        with self._lock:
            return [
                (f, n) for f, ns in self._dests.items() for n in sorted(ns)
            ]

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "routes.count": sum(len(ns) for ns in self._dests.values()),
                "topics.count": len(self._dests),
            }
