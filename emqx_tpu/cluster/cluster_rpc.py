"""Transactional cluster-wide config multicall log.

Reference analog: `emqx_cluster_rpc` (apps/emqx_conf/src/emqx_cluster_rpc.erl:
20-30) — cluster config mutations append to a replicated transaction log in
mnesia; each node keeps a per-node commit cursor, applies entries in order,
and can catch up / skip / fast-forward after being down.

Here the initiating node assigns the next txn id under the cluster's
log-writer role (the node with the lexicographically smallest name — a
deterministic stand-in for mnesia's transaction serialization), replicates
the entry, and every node applies through its registered handler table.
A node that was partitioned replays missed entries on `catch_up`.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

Handler = Callable[..., Any]


class ClusterRpcLog:
    """Replicated ordered log of named operations with a commit cursor."""

    def __init__(self, node: str) -> None:
        self.node = node
        self._lock = threading.Lock()
        self._log: List[Tuple[int, str, tuple]] = []  # (txn_id, op, args)
        self._cursor = 0  # last applied txn id
        self._handlers: Dict[str, Handler] = {}
        self._skipped: List[int] = []

    def register_handler(self, op: str, handler: Handler) -> None:
        self._handlers[op] = handler

    # -- log writer side ---------------------------------------------------
    def append(self, op: str, args: tuple) -> Tuple[int, str, tuple]:
        with self._lock:
            txn_id = (self._log[-1][0] + 1) if self._log else 1
            entry = (txn_id, op, args)
            self._log.append(entry)
        return entry

    def receive(self, entry: Tuple[int, str, tuple]) -> None:
        """Accept a replicated entry (idempotent, order-tolerant)."""
        with self._lock:
            known = {e[0] for e in self._log}
            if entry[0] not in known:
                self._log.append(entry)
                self._log.sort(key=lambda e: e[0])

    # -- apply side --------------------------------------------------------
    def apply_pending(self) -> int:
        """Apply every entry past the cursor, in txn order.

        A handler raising marks the txn skipped (the reference's `skip`
        resolution for a failed MFA) and the cursor still advances —
        matching emqx_cluster_rpc's operator-driven skip/fast_forward.
        """
        applied = 0
        while True:
            with self._lock:
                nxt = None
                for e in self._log:
                    if e[0] == self._cursor + 1:
                        nxt = e
                        break
                if nxt is None:
                    return applied
            txn_id, op, args = nxt
            handler = self._handlers.get(op)
            try:
                if handler is None:
                    raise KeyError(f"no handler for {op}")
                handler(*args)
            except Exception:
                with self._lock:
                    self._skipped.append(txn_id)
            with self._lock:
                self._cursor = txn_id
            applied += 1

    def fast_forward(self, to_txn: int) -> None:
        with self._lock:
            self._cursor = max(self._cursor, to_txn)

    # -- views / catch-up --------------------------------------------------
    @property
    def cursor(self) -> int:
        with self._lock:
            return self._cursor

    @property
    def skipped(self) -> List[int]:
        with self._lock:
            return list(self._skipped)

    def entries_after(self, txn_id: int) -> List[Tuple[int, str, tuple]]:
        with self._lock:
            return [e for e in self._log if e[0] > txn_id]

    def catch_up_from(self, entries: List[Tuple[int, str, tuple]]) -> int:
        for e in entries:
            self.receive(tuple(e))
        return self.apply_pending()
