"""Real TCP cluster transport (gen_rpc data-plane analog).

Implements the same bus interface as `transport.LocalBus` (attach/detach/
send/cast) over length-prefixed frames on TCP sockets, so two actual OS
processes — or machines — can cluster. Reference analog: gen_rpc's
multi-channel TCP with per-key stable channel selection
(apps/emqx/src/emqx_rpc.erl:66-80).

Design:
- one `TcpBus` per node: a listening socket + an acceptor thread; outbound
  connections are created on demand, `channels` sockets per peer, picked by
  `hash(channel_key)` so one topic's forwards never reorder while unrelated
  topics flow in parallel (emqx_broker.erl:278-293 keyed forwards);
- frames: 4-byte big-endian length + pickled (kind, req_id, payload);
  kinds: hello / call / cast / reply. Pickle implies the cluster port must
  only be reachable by trusted peers — the same trust model as distributed
  Erlang behind its cookie (EMQX deployments firewall the distribution
  ports identically);
- `send` is a synchronous call with timeout -> NodeUnreachable on connect
  failure, broken pipe, or deadline; one reconnect attempt per send covers
  peer restarts (gen_rpc {badtcp,...} -> error semantics);
- inbound handler runs sequentially per connection, preserving per-channel
  FIFO; replies carry either a value or a pickled exception message that
  re-raises as RemoteCallError at the caller.
"""

from __future__ import annotations

import pickle
import random
import socket
import struct
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from emqx_tpu.cluster.transport import NodeUnreachable
from emqx_tpu.observe import faults as _faults
from emqx_tpu.observe.faults import FaultError

Handler = Callable[[str, object], Optional[object]]

_LEN = struct.Struct(">I")
MAX_FRAME = 64 * 1024 * 1024


class RemoteCallError(Exception):
    """The remote handler raised; message carries the remote repr."""


def _send_frame(sock: socket.socket, obj: object) -> None:
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> object:
    (n,) = _LEN.unpack(_recv_exact(sock, 4))
    if n > MAX_FRAME:
        raise ConnectionError(f"frame too large: {n}")
    return pickle.loads(_recv_exact(sock, n))


class _PeerConn:
    """One outbound socket to a peer: framed, request-id multiplexed."""

    def __init__(self, bus: "TcpBus", dst: str, addr: Tuple[str, int]):
        self.bus = bus
        self.dst = dst
        self.sock = socket.create_connection(addr, timeout=bus.timeout)
        self.sock.settimeout(None)
        self.wlock = threading.Lock()
        self.lock = threading.Lock()
        self._next_id = 0
        self._pending: Dict[int, list] = {}  # rid -> [event, ok, value]
        self.alive = True
        # hello carries (name, listen_host, listen_port) so the accepting
        # side can auto-register the dialer as a peer — a seed node then
        # reaches joiners it was never configured with (autocluster join)
        _send_frame(
            self.sock, ("hello", 0, (bus.node, bus.host, bus.port))
        )
        t = threading.Thread(target=self._reader, daemon=True)
        t.start()

    def _reader(self) -> None:
        try:
            while True:
                kind, rid, payload = _recv_frame(self.sock)
                if kind == "reply":
                    ok, value = payload
                    with self.lock:
                        ent = self._pending.pop(rid, None)
                    if ent is not None:
                        ent[1], ent[2] = ok, value
                        ent[0].set()
        except (ConnectionError, OSError):
            pass
        finally:
            self.close()

    def close(self) -> None:
        self.alive = False
        try:
            self.sock.close()
        except OSError:
            pass
        with self.lock:
            pending, self._pending = self._pending, {}
        for ent in pending.values():
            ent[0].set()  # waiters see alive=False / no value

    def call(self, payload: object, timeout: float) -> object:
        ev = threading.Event()
        ent = [ev, None, None]
        with self.lock:
            rid = self._next_id = self._next_id + 1
            self._pending[rid] = ent
        try:
            with self.wlock:
                _send_frame(self.sock, ("call", rid, payload))
        except OSError as e:
            self.close()
            raise NodeUnreachable(f"{self.bus.node} -> {self.dst}: {e}")
        if not ev.wait(timeout) or ent[1] is None:
            with self.lock:
                self._pending.pop(rid, None)
            if not self.alive:
                raise NodeUnreachable(f"{self.bus.node} -> {self.dst}: closed")
            raise NodeUnreachable(f"{self.bus.node} -> {self.dst}: timeout")
        if ent[1] is False:
            raise RemoteCallError(ent[2])
        return ent[2]

    def cast(self, payload: object) -> None:
        with self.wlock:
            _send_frame(self.sock, ("cast", 0, payload))


class TcpBus:
    """LocalBus-compatible transport over real TCP sockets."""

    def __init__(
        self,
        node: str,
        host: str = "127.0.0.1",
        port: int = 0,
        channels: int = 4,
        timeout: float = 5.0,
        send_retries: int = 2,
        send_backoff_s: float = 0.05,
        send_deadline_s: float = 0.0,
        metrics=None,
        degrade=None,
    ):
        """`send_retries`/`send_backoff_s`/`send_deadline_s`: each `send`
        retries transient transport failures with bounded exponential
        backoff + jitter under an overall deadline (0 = timeout *
        (retries + 1)) before NodeUnreachable — replacing the old
        single-reconnect-per-send. Gives-up count into
        `cluster.send.dead_letter`. `degrade`: an optional
        DegradeController — sends to a tripped destination fail FAST
        (no deadline burn) until the half-open probe recovers it."""
        self.node = node
        self.timeout = timeout
        self.channels = channels
        self.send_retries = max(0, int(send_retries))
        self.send_backoff_s = float(send_backoff_s)
        self.send_deadline_s = float(send_deadline_s)
        self.degrade = degrade
        if metrics is None:
            from emqx_tpu.broker.metrics import default_metrics

            metrics = default_metrics
        self.metrics = metrics
        self._send_rng = random.Random(0xC1)
        self._handler: Optional[Handler] = None
        self._peers: Dict[str, Tuple[str, int]] = {}
        self._conns: Dict[Tuple[str, int], _PeerConn] = {}
        self._inbound: set = set()
        self._lock = threading.Lock()
        self._stopping = False
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.host, self.port = self._srv.getsockname()
        self._accept_thread = threading.Thread(target=self._accept, daemon=True)
        self._accept_thread.start()

    # -- LocalBus interface -------------------------------------------------
    def attach(self, node: str, handler: Handler) -> None:
        assert node == self.node, "TcpBus serves exactly its own node"
        self._handler = handler

    def detach(self, node: str) -> None:
        if node == self.node:
            self._handler = None

    def nodes(self) -> list:
        with self._lock:
            return sorted([self.node, *self._peers])

    def add_peer(self, name: str, host: str, port: int) -> None:
        with self._lock:
            self._peers[name] = (host, port)

    def remove_peer(self, name: str) -> None:
        with self._lock:
            self._peers.pop(name, None)
            stale = [k for k in self._conns if k[0] == name]
            conns = [self._conns.pop(k) for k in stale]
        for c in conns:
            c.close()

    def send(
        self, src: str, dst: str, payload: object, channel_key: str = ""
    ) -> object:
        """Confirmed send with deadline + bounded retry/backoff.

        Runs on forward/replication worker threads (never the event
        loop), so the backoff sleeps are plain `time.sleep`. A breaker
        (when a DegradeController is attached) makes a partitioned
        destination fail fast instead of paying the full deadline per
        message; give-up counts into `cluster.send.dead_letter` — the
        bounded dead-letter record for the caller's at-least-once layer.
        """
        br = (
            self.degrade.cluster_breaker(dst)
            if self.degrade is not None
            else None
        )
        if br is not None and not br.allow():
            self.metrics.inc("cluster.send.dead_letter")
            raise NodeUnreachable(f"{self.node} -> {dst}: circuit open")
        deadline = time.monotonic() + (
            self.send_deadline_s
            or self.timeout * (self.send_retries + 1)
        )
        delay = self.send_backoff_s
        attempt = 0
        while True:
            try:
                # fault site: an injected partition/drop exercises the
                # same retry + dead-letter ladder as a real one
                act = _faults.hit("cluster.forward")
                if act == "drop":
                    raise FaultError("cluster.forward")
                budget = deadline - time.monotonic()
                if budget <= 0:
                    raise NodeUnreachable(
                        f"{self.node} -> {dst}: send deadline exceeded"
                    )
                result = self._conn_for(dst, channel_key).call(
                    payload, min(self.timeout, budget)
                )
                if br is not None:
                    br.record_success()
                return result
            except (NodeUnreachable, FaultError, OSError) as e:
                attempt += 1
                if (
                    attempt > self.send_retries
                    or time.monotonic() + delay >= deadline
                ):
                    if br is not None:
                        br.record_failure("send")
                    self.metrics.inc("cluster.send.dead_letter")
                    if isinstance(e, NodeUnreachable):
                        raise
                    raise NodeUnreachable(
                        f"{self.node} -> {dst}: {e}"
                    ) from e
                self.metrics.inc("cluster.send.retries")
                time.sleep(
                    delay * (1.0 + 0.5 * self._send_rng.random())
                )
                delay = min(delay * 2.0, self.timeout)

    def cast(
        self, src: str, dst: str, payload: object, channel_key: str = ""
    ) -> bool:
        try:
            if _faults.hit("cluster.forward") == "drop":
                return False  # casts are lossy by contract
            self._conn_for(dst, channel_key).cast(payload)
            return True
        except (NodeUnreachable, FaultError, OSError):
            return False

    # -- internals ----------------------------------------------------------
    def _conn_for(self, dst: str, channel_key: str) -> _PeerConn:
        with self._lock:
            addr = self._peers.get(dst)
        if addr is None:
            raise NodeUnreachable(f"{self.node} -> {dst}: unknown peer")
        ch = hash(channel_key) % self.channels
        key = (dst, ch)
        with self._lock:
            conn = self._conns.get(key)
        if conn is not None and conn.alive:
            return conn
        # (re)connect — one attempt per send, covering peer restarts
        try:
            conn = _PeerConn(self, dst, addr)
        except OSError as e:
            raise NodeUnreachable(f"{self.node} -> {dst}: {e}")
        with self._lock:
            cur = self._conns.get(key)
            if cur is not None and cur.alive:
                conn.close()
                return cur
            self._conns[key] = conn
        return conn

    def _accept(self) -> None:
        while not self._stopping:
            try:
                sock, _addr = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(sock,), daemon=True
            ).start()

    def _serve_conn(self, sock: socket.socket) -> None:
        peer = "?"
        with self._lock:
            self._inbound.add(sock)
        try:
            kind, _rid, payload = _recv_frame(sock)
            if kind != "hello":
                return
            if isinstance(payload, tuple):
                peer, phost, pport = payload
                with self._lock:
                    # learn the dialer's listen address (don't clobber an
                    # explicit add_peer with a stale announce)
                    self._peers.setdefault(peer, (phost, pport))
            else:  # legacy hello: bare node name
                peer = payload
            wlock = threading.Lock()
            while True:
                kind, rid, payload = _recv_frame(sock)
                handler = self._handler
                if kind == "call":
                    try:
                        if handler is None:
                            raise RuntimeError("node not attached")
                        result = handler(peer, payload)
                        reply = ("reply", rid, (True, result))
                    except Exception as e:  # noqa: BLE001 — ship to caller
                        reply = ("reply", rid, (False, repr(e)))
                    with wlock:
                        _send_frame(sock, reply)
                elif kind == "cast" and handler is not None:
                    try:
                        handler(peer, payload)
                    except Exception:  # noqa: BLE001 — casts are lossy
                        pass
        except (ConnectionError, OSError):
            pass
        finally:
            with self._lock:
                self._inbound.discard(sock)
            try:
                sock.close()
            except OSError:
                pass

    def stop(self) -> None:
        self._stopping = True
        self._handler = None
        # shutdown() unblocks the acceptor thread stuck in accept(2) — a
        # bare close() would leave the kernel socket (and the port) alive
        # until the blocked syscall returns, failing later rebinds
        try:
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        self._accept_thread.join(timeout=2)
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
            inbound = list(self._inbound)
            self._inbound.clear()
        for c in conns:
            c.close()
        for s in inbound:
            try:
                s.close()
            except OSError:
                pass
