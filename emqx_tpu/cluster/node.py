"""ClusterNode: one broker node wired into the cluster fabric.

Composes the local pub/sub kernel (`Broker`) with:
- membership (ekka parity) with route GC on nodedown
  (emqx_router_helper.erl:96,135-148),
- the replicated route table (mria parity),
- BPAPI-versioned RPC protos: broker-forward, route replication, channel
  registry, cluster config log — mirroring the reference's four proto
  families (apps/emqx/src/proto/: broker, cm, persistent_session, emqx),
- cross-node publish forwarding with per-node aggre dedup
  (emqx_broker.erl:262-293): ONE forward per (message, node) carrying the
  matched filters so the owner node skips re-matching,
- cluster-wide clientid→node channel registry (emqx_cm_registry parity),
- replicated config transaction log (emqx_cluster_rpc parity).

`make_cluster(n)` builds an n-node in-process cluster on a LocalBus — the
analog of the reference's slave-node CT harness
(emqx_router_helper_SUITE.erl:61, emqx_cluster_rpc_SUITE.erl:25-27).
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.message import Message
from emqx_tpu.broker.shared_sub import stable_hash
from emqx_tpu.cluster.cluster_rpc import ClusterRpcLog
from emqx_tpu.cluster.membership import Membership
from emqx_tpu.cluster.route_sync import ClusterRouteTable, ShardOwnership
from emqx_tpu.cluster.rpc import Rpc, RpcError
from emqx_tpu.cluster.transport import LocalBus
from emqx_tpu.mqtt import packet as pkt
from emqx_tpu.ops import topics as T


class ClusterNode:
    def __init__(
        self,
        name: str,
        bus: LocalBus,
        clock: Optional[Callable[[], float]] = None,
        broker: Optional[Broker] = None,
        forward_mode: str = "async",
        loop=None,
    ) -> None:
        """`loop`: when this node wraps a LIVE BrokerApp broker, incoming
        rpc handlers must run on the app's event loop — a forward's
        dispatch writes to client sockets, which asyncio transports only
        allow from their own thread. The bus thread then blocks on the
        loop's result (calls need replies); casts drain the same way."""
        self.name = name
        self.bus = bus
        self._loop = loop
        # app mode: replication rpcs must not block the event loop on a
        # peer round-trip (and an in-process peer pair would deadlock) —
        # a SINGLE worker preserves add/delete ordering per node
        self._repl_pool = (
            ThreadPoolExecutor(max_workers=1, thread_name_prefix=f"repl-{name}")
            if loop is not None
            else None
        )
        # forwards get their OWN ordered worker: a slow receiver (cold
        # jit compile holds the confirmed reply up to ~40s) must not
        # stall route replication / shared-group / drain traffic
        self._fwd_pool = (
            ThreadPoolExecutor(max_workers=1, thread_name_prefix=f"fwd-{name}")
            if loop is not None
            else None
        )
        self.broker = broker or Broker()
        self.routes = ClusterRouteTable(name)
        # mesh-slice ownership (scale-out serving): which node serves
        # which slice of the global subscriber-lane space, and where
        # publishes bound for a dead owner reroute (docs/scale_out.md)
        self.shards = ShardOwnership(name, metrics=self.broker.metrics)
        self.membership = Membership(name, bus, clock=clock)
        self.rpc = Rpc(name, bus)
        self.conf_log = ClusterRpcLog(name)
        self.forward_mode = forward_mode
        self._chan_lock = threading.Lock()
        # clientid -> (node, sid): replicated channel registry
        self._channels: Dict[str, Tuple[str, str]] = {}
        # persistent-session router state (emqx_session_router parity):
        # locally parked sessions + the replicated clientid -> owner map
        self._parked: Dict[str, Dict] = {}
        self._parked_owner: Dict[str, str] = {}
        # guards park["pending"] swaps vs concurrent banking: in library
        # (sync) mode rpc handlers run on bus threads while drain_to
        # runs on the caller thread
        self._park_lock = threading.Lock()
        # (real, group) -> set of nodes holding members; exactly one of
        # them dispatches each message (per-message rotation in
        # shared_leader) — a group spanning nodes delivers exactly once
        # (emqx_shared_sub's cluster-wide mnesia member table)
        self._shared_nodes: Dict[Tuple[str, str], set] = {}
        # cached sorted candidate lists, invalidated on membership change
        self._shared_cands: Dict[Tuple[str, str], List[str]] = {}
        self._retainer = None  # set by attach_retainer (app mode)
        # topics touched by LIVE retain casts while a join-time bootstrap
        # is in flight: the (older) dump must not resurrect them
        self._retain_boot_seen: Optional[set] = None
        self._register_protos()
        self.membership.monitor(self._on_membership)
        bus.attach(name, self._handle)
        # the broker replicates routes / shared membership through this
        # node from now on (broker.subscribe/unsubscribe seam)
        self.broker.cluster = self
        self.broker.shared.leader_check = self.shared_leader

    # -- wiring ------------------------------------------------------------
    def _handle(self, from_node: str, payload):
        kind = payload[0]
        if kind == "membership":
            return self.membership.handle(from_node, payload)
        if kind == "rpc":
            if self._loop is not None and not self._loop.is_closed():
                import asyncio as _aio
                import concurrent.futures

                fut: concurrent.futures.Future = concurrent.futures.Future()

                def run():
                    try:
                        res = self.rpc.handle(from_node, payload)
                        # ASYNC handler (e.g. forward_batch's device
                        # dispatch): the reply — and thus the sender's
                        # QoS1 confirm — resolves only after the actual
                        # dispatch completes, while the loop stays free
                        if (
                            isinstance(res, tuple)
                            and len(res) == 2
                            and res[0] == "ok"
                            and _aio.iscoroutine(res[1])
                        ):
                            t = self._loop.create_task(res[1])

                            def done(t):
                                exc = (
                                    t.exception()
                                    if not t.cancelled()
                                    else _aio.CancelledError()
                                )
                                if exc:
                                    fut.set_exception(exc)
                                else:
                                    fut.set_result(("ok", t.result()))

                            t.add_done_callback(done)
                        else:
                            fut.set_result(res)
                    except BaseException as e:  # reply errors to caller
                        fut.set_exception(e)

                self._loop.call_soon_threadsafe(run)
                # generous: a forwarded batch can trigger a jit compile
                # (~10-40s cold) before the handler returns
                return fut.result(timeout=120)
            return self.rpc.handle(from_node, payload)
        return None

    def _register_protos(self) -> None:
        self.rpc.registry.register(
            "broker",
            1,
            {
                "forward": self._proto_forward,
                "forward_batch": self._proto_forward_batch,
            },
        )
        self.rpc.registry.register(
            "route",
            1,
            {
                "add_route": self.routes.add_route,
                "delete_route": self.routes.delete_route,
                "dump": self.routes.dump,
            },
        )
        self.rpc.registry.register(
            "cm",
            1,
            {
                "insert_channel": self._proto_insert_channel,
                "delete_channel": self._proto_delete_channel,
                "lookup_channel": self.lookup_channel,
                "discard": self._proto_discard,
            },
        )
        self.rpc.registry.register(
            "conf",
            1,
            {
                "append": self.conf_log.append,
                "receive_apply": self._proto_conf_receive_apply,
                "entries_after": self.conf_log.entries_after,
            },
        )
        self.rpc.registry.register(
            "shared",
            1,
            {
                "join": self._proto_shared_join,
                "leave": self._proto_shared_leave,
                "dump": self._proto_shared_dump,
            },
        )
        self.rpc.registry.register(
            "shard",
            1,
            {
                "advertise": self._proto_shard_advertise,
                "dump": self.shards.dump,
            },
        )
        self.rpc.registry.register(
            "retain",
            1,
            {
                "store": self._proto_retain_store,
                "dump": self._proto_retain_dump,
            },
        )
        # v2 adds the PAGED bootstrap read (a 5-10M retained store must
        # not ship as one multi-GB RPC reply); v1 stays frozen for
        # old-version peers (BPAPI evolution rules)
        self.rpc.registry.register(
            "retain",
            2,
            {
                "store": self._proto_retain_store,
                "dump": self._proto_retain_dump,
                "dump_page": self._proto_retain_dump_page,
            },
        )
        self.rpc.registry.register(
            "sess",
            1,
            {
                "insert_parked": self._proto_insert_parked,
                "delete_parked": self._proto_delete_parked,
                "resume_begin": self._proto_resume_begin,
                "resume_end": self._proto_resume_end,
                "dump_parked": self._proto_dump_parked,
            },
        )
        # v2 adds the drain/rolling-upgrade handoff (BPAPI discipline:
        # v1 is frozen, new behavior = new version carrying the union)
        self.rpc.registry.register(
            "sess",
            2,
            {
                "insert_parked": self._proto_insert_parked,
                "delete_parked": self._proto_delete_parked,
                "resume_begin": self._proto_resume_begin,
                "resume_end": self._proto_resume_end,
                "dump_parked": self._proto_dump_parked,
                "park_remote": self._proto_park_remote,
                "park_append": self._proto_park_append,
            },
        )

    def _on_membership(self, event: str, node: str) -> None:
        if event == "node_down":
            # sessions parked on a dead node are unreachable until it
            # returns: purge the owner entries so reconnecting clients get
            # fresh sessions instead of resume limbo (route-GC semantics)
            gone = [
                cid for cid, n in self._parked_owner.items() if n == node
            ]
            for cid in gone:
                self._parked_owner.pop(cid, None)
            purged = self.routes.cleanup_node(node)
            with self._chan_lock:
                for cid, (n, _) in list(self._channels.items()):
                    if n == node:
                        del self._channels[cid]
            self.rpc.forget_peer(node)
            # shared-group leadership: a dead node's members are gone;
            # surviving member nodes take over dispatch
            for key, nodes in list(self._shared_nodes.items()):
                nodes.discard(node)
                if not nodes:
                    self._shared_nodes.pop(key, None)
            self._shared_cands.clear()
            # shard re-own rides the same degrade ladder that declared
            # the node dead (heartbeat expiry / open breakers): the dead
            # owner's mesh slices move to rendezvous-chosen survivors,
            # so forwards reroute to a live slice instead of stalling
            # behind the dead peer's send deadline (docs/scale_out.md)
            moves = self.shards.reown(
                node, self.membership.running_nodes()
            )
            if moves:
                import logging

                logging.getLogger("emqx_tpu.cluster").warning(
                    "node %s down: re-owned shards %s", node, moves
                )
            self.broker.metrics.inc("cluster.nodedown.routes_purged", purged)
        elif event == "node_up":
            self.rpc.forget_peer(node)  # re-negotiate BPAPI versions

    # -- lifecycle ---------------------------------------------------------
    def join(self, seed: str) -> bool:
        """Join the cluster: membership, route bootstrap, conf catch-up."""
        if not self.membership.join(seed):
            return False
        # pull the seed's route replica (mria replicant catch-up)
        self.routes.load(self.rpc.call(seed, "route", "dump"))
        # push our own local routes to everyone
        mine = [(f, ns) for f, ns in self.routes.dump() if self.name in ns]
        for peer in self.membership.peers():
            for f, _ in mine:
                self.rpc.cast(peer, "route", "add_route", f, self.name, key=f)
        # config log catch-up
        entries = self.rpc.call(seed, "conf", "entries_after", self.conf_log.cursor)
        self.conf_log.catch_up_from([tuple(e) for e in entries])
        # parked-session owner map bootstrap (a late joiner must be able
        # to resume sessions parked before it joined)
        self._parked_owner.update(
            self.rpc.call(seed, "sess", "dump_parked")
        )
        # mesh-shard ownership bootstrap + (re-)announce our own slice:
        # a returning owner reclaims its home shards here (the
        # advertisement IS the reclaim — see ShardOwnership.advertise)
        try:
            if self.rpc.supported_version(seed, "shard") >= 1:
                self.shards.load(self.rpc.call(seed, "shard", "dump"))
                mine = self.shards.local_shards()
                if mine:
                    self._shard_cast()
        except RpcError:
            pass  # pre-shard-proto seed: ownership stays local-only
        # shared-group membership bootstrap + announce our own groups
        for r, g, nodes in self.rpc.call(seed, "shared", "dump"):
            self._shared_nodes.setdefault((r, g), set()).update(nodes)
            self._shared_cands.pop((r, g), None)
        for real, groups in self.broker.shared._table.items():
            for gname in groups:
                self.shared_join(real, gname)
        # retained-store bootstrap, both directions (late joiner catches
        # up on the seed's set; its own pre-join retained pushes out like
        # routes do). The dump applies ON THE LOOP in app mode — the
        # retainer trie has no lock, and live casts are already
        # loop-marshalled; `_retain_boot_seen` stops the older dump from
        # resurrecting a topic a concurrent live cast just set/cleared.
        if self._retainer is not None:
            self._retain_boot_seen = set()
            try:

                def apply_page(page):
                    seen = self._retain_boot_seen or set()
                    for mjson in page:
                        if mjson.get("topic") not in seen:
                            self._proto_retain_store(mjson)

                # the local pre-join snapshot is taken ON THE LOOP (and
                # BEFORE any page applies, so the seed's own set never
                # re-replicates back out): the retainer trie has no lock
                # and listeners already serve during join retries — an
                # executor-thread walk could tear mid-mutation
                local = self._call_on_loop(self._retainer.all_messages)
                if self.rpc.supported_version(seed, "retain") >= 2:
                    # paged bootstrap: bounded pages instead of one
                    # multi-GB reply at 5-10M retained messages; each
                    # page applies on the loop before the next is pulled
                    cursor = None
                    while True:
                        page, cursor = self.rpc.call(
                            seed, "retain", "dump_page", cursor,
                            self.RETAIN_PAGE_MAX,
                        )
                        self._call_on_loop(lambda p=page: apply_page(p))
                        if cursor is None:
                            break
                else:
                    dump = self.rpc.call(seed, "retain", "dump")
                    self._call_on_loop(lambda: apply_page(dump))
                for m in local:
                    self._replicate_retain(m)
            except RpcError as e:
                import logging

                logging.getLogger("emqx_tpu.cluster").warning(
                    "retained bootstrap from %s failed: %s", seed, e
                )
                self.broker.metrics.inc("cluster.retain.bootstrap_failed")
            finally:
                self._retain_boot_seen = None
        return True

    def _call_on_loop(self, fn, timeout: float = 120.0):
        """Run `fn` on the app event loop (when one is attached) from a
        bus/executor thread; synchronous fallback in library mode."""
        if self._loop is None or self._loop.is_closed():
            return fn()
        import concurrent.futures

        fut: "concurrent.futures.Future" = concurrent.futures.Future()

        def run():
            try:
                fut.set_result(fn())
            except BaseException as e:
                fut.set_exception(e)

        self._loop.call_soon_threadsafe(run)
        return fut.result(timeout=timeout)

    def leave(self) -> None:
        # the pool REFERENCES are construction-only (CX discipline):
        # shutdown() flips state inside the executors themselves, so a
        # loop-side submit racing this drain (leave runs on the default
        # executor during a rolling-upgrade handoff) gets a RuntimeError
        # that `_pool_submit` drops — never a torn None dereference
        if self._repl_pool is not None:
            self._repl_pool.shutdown(wait=True)  # flush pending replication
        if self._fwd_pool is not None:
            self._fwd_pool.shutdown(wait=True)  # flush in-flight forwards
        self.membership.leave()
        self.rpc.stop()
        self.bus.detach(self.name)

    @staticmethod
    def _pool_submit(pool, fn, *args) -> None:
        """Submit replication/forward work to an app-mode pool. A pool
        already shut down by a racing leave() swallows the task — the
        bus is detaching, the work has nowhere to go."""
        try:
            pool.submit(fn, *args)
        except RuntimeError:
            pass

    # -- subscribe side ----------------------------------------------------
    def subscribe(
        self,
        sid: str,
        client_id: str,
        filter_: str,
        opts: pkt.SubOpts,
        deliver,
    ) -> None:
        """Route replication + shared membership happen inside the broker
        seam (broker.cluster points back here), so library callers and
        the live app share one code path."""
        self.broker.subscribe(sid, client_id, filter_, opts, deliver)

    def unsubscribe(self, sid: str, filter_: str) -> bool:
        return self.broker.unsubscribe(sid, filter_)

    def _replicate_add(self, filter_: str) -> None:
        self.routes.add_route(filter_, self.name)
        self._replicate("add_route", filter_)

    def _replicate_delete(self, filter_: str) -> None:
        self.routes.delete_route(filter_, self.name)
        self._replicate("delete_route", filter_)

    def _replicate(self, method: str, filter_: str) -> None:
        """Wildcards replicate transactionally (maybe_trans,
        emqx_router.erl:118-121 — a torn trie edge breaks matching);
        exact topics ride ordered casts. In app mode both ship through
        the replication worker so the event loop never blocks on a peer
        round-trip (ordering preserved: one worker, FIFO submits)."""
        peers = self.membership.peers()

        def one(p):
            if T.wildcard(filter_):
                try:
                    self.rpc.call(p, "route", method, filter_, self.name)
                except RpcError:
                    pass  # peer down: membership GC will reconcile
            else:
                self.rpc.cast(p, "route", method, filter_, self.name, key=filter_)

        if self._repl_pool is not None:
            for p in peers:
                self._pool_submit(self._repl_pool, one, p)
        else:
            for p in peers:
                one(p)

    # -- mesh-shard ownership (scale-out serving) --------------------------
    def attach_mesh_slice(
        self, mesh_shape, index: int = 0, total: int = 1
    ) -> List[str]:
        """Declare this node's slice of the global subscriber-lane
        space: slice `index` of `total`, served by a local mesh of
        `mesh_shape` = (dp, tp). Advertised to every current peer (late
        joiners pull the dump). The serving engine's span label
        (`router.device_step` shard attr) follows the advertisement."""
        shards = self.shards.advertise_local(
            tuple(mesh_shape), index, total
        )
        self.broker.shard_label = self.shards.label()
        dev = self.broker._device
        if dev is not None and hasattr(dev, "shard_label"):
            dev.shard_label = self.broker.shard_label
        self._shard_cast()
        return shards

    def _shard_cast(self) -> None:
        mine = self.shards.local_shards()
        if not mine:
            return
        shape = list(
            self.shards._home.get(self.name, ((), (0, 0)))[1]
        )

        def one(p):
            self.rpc.cast(
                p, "shard", "advertise", self.name, mine, shape,
                key="shard",
            )

        for p in self.membership.peers():
            if self._repl_pool is not None:
                self._pool_submit(self._repl_pool, one, p)
            else:
                one(p)

    def _proto_shard_advertise(self, node: str, shards, shape) -> None:
        self.shards.advertise(node, list(shards), tuple(shape))

    def _live_dest(self, node: str) -> str:
        """Remap a publish destination whose owner is DOWN to the node
        that re-owned its shard (rendezvous successor). While membership
        still believes the owner is alive — or no successor exists —
        the original destination stands and the send path's breaker/
        retry ladder handles it."""
        if node == self.name or self.membership.is_alive(node):
            return node
        alt = self.shards.successor_node(node)
        if alt is not None and alt != node:
            self.broker.metrics.inc("mesh.shard.reroutes")
            return alt
        return node

    # -- publish side ------------------------------------------------------
    def publish(self, msg: Message) -> int:
        """Cluster publish: match once, dispatch local, forward per node."""
        rec = getattr(self.broker, "spans", None)
        sp = rec.publish_begin(msg) if rec is not None else None
        msg = self.broker.hooks.run_fold("message.publish", (), msg)
        if msg is None or msg.headers.get("allow_publish") is False:
            self.broker.metrics.inc("messages.dropped")
            if sp is not None:
                rec.finish_span(sp, 0, status="error")
            return 0
        dests = self.routes.match_dests(msg.topic)
        n = self._dispatch_dests(msg, dests)
        if sp is not None:
            rec.finish_span(sp, n)
        return n

    def publish_batch(self, msgs: Sequence[Message]) -> int:
        """One route-table match kernel for the whole batch, then fan out.

        Remote fan-out is batched per destination node: a single
        forward_batch per (batch, node) instead of per (message, node) —
        the batching the TPU design adds over the reference hot path.
        """
        kept: List[Message] = []
        for m in msgs:
            m = self.broker.hooks.run_fold("message.publish", (), m)
            if m is not None and m.headers.get("allow_publish") is not False:
                kept.append(m)
        all_dests = self.routes.match_dests_batch([m.topic for m in kept])
        total = 0
        per_node: Dict[str, List[Tuple[Message, List[str]]]] = {}
        for m, dests in zip(kept, all_dests):
            for node, filters in dests.items():
                node = self._live_dest(node)
                if node == self.name:
                    total += self.broker.dispatch(filters, m)
                else:
                    per_node.setdefault(node, []).append((m, filters))
        for node, batch in per_node.items():
            self.rpc.cast(node, "broker", "forward_batch", batch, key=node)
            total += sum(1 for _ in batch)
        return total

    # -- cluster-wide retained store ---------------------------------------
    def attach_retainer(self, retainer, hooks) -> None:
        """Replicate the retained store cluster-wide (the reference's
        retainer rides a replicated mnesia table, emqx_retainer_mnesia;
        here retained set/clear ops ride ordered casts and a join-time
        bootstrap): a subscriber on ANY node replays retained messages
        published on any other."""
        self._retainer = retainer

        def on_pub(msg):
            if (
                msg is not None
                and msg.retain
                and not msg.headers.get("retain_replicated")
            ):
                self._replicate_retain(msg)
            return None

        # priority below the retainer's own store hook: replicate what
        # was actually accepted locally
        hooks.add("message.publish", on_pub, priority=90,
                  tag="cluster.retain_replicate")

    def _replicate_retain(self, msg: Message) -> None:
        from emqx_tpu.storage.codec import msg_to_json

        mjson = msg_to_json(msg)

        def one(p):
            self.rpc.cast(p, "retain", "store", mjson, key=msg.topic)

        for p in self.membership.peers():
            if self._repl_pool is not None:
                self._pool_submit(self._repl_pool, one, p)
            else:
                one(p)

    RETAIN_DUMP_CAP = 100_000

    def _proto_retain_store(self, mjson) -> None:
        if self._retainer is None:
            return
        msg = self._msg_from(mjson)
        if self._retain_boot_seen is not None:
            # a live cast during OUR bootstrap window: the dump snapshot
            # is older than this op and must not override it
            self._retain_boot_seen.add(msg.topic)
        # straight into the store — NOT the publish fold — so replicas
        # never re-replicate or re-dispatch (empty payload = clear, the
        # same MQTT semantics on_publish already implements)
        msg.headers["retain_replicated"] = True
        self._retainer.on_publish(msg)

    def _proto_retain_dump(self):
        """LEGACY (retain v1) join-time bootstrap: the seed's retained
        set in one reply, capped. v2 peers use the paged read."""
        from emqx_tpu.storage.codec import msg_to_json

        if self._retainer is None:
            return []
        msgs = self._retainer.all_messages(limit=self.RETAIN_DUMP_CAP + 1)
        if len(msgs) > self.RETAIN_DUMP_CAP:
            self.broker.metrics.inc("cluster.retain.dump_truncated")
            msgs = msgs[: self.RETAIN_DUMP_CAP]
        return [msg_to_json(m) for m in msgs]

    RETAIN_PAGE_MAX = 5000

    def _proto_retain_dump_page(self, after, limit):
        """Paged bootstrap read (retain v2): ordered cursor walk, each
        page a bounded RPC reply — a 5-10M-message store bootstraps in
        bounded memory (emqx_retainer_mnesia.erl:146-152 paged-read
        parity). Returns (page_json, next_cursor | None)."""
        from emqx_tpu.storage.codec import msg_to_json

        if self._retainer is None:
            return [], None
        msgs, nxt = self._retainer.messages_page(
            after, min(int(limit), self.RETAIN_PAGE_MAX)
        )
        return [msg_to_json(m) for m in msgs], nxt

    # -- cluster-wide shared groups ----------------------------------------
    def shared_join(self, real: str, group: str) -> None:
        """First local member of (real, group): announce membership so
        every node agrees on the group leader."""
        self._shared_nodes.setdefault((real, group), set()).add(self.name)
        self._shared_cands.pop((real, group), None)
        self._shared_cast("join", real, group)

    def shared_leave(self, real: str, group: str) -> None:
        self._proto_shared_leave(real, group, self.name)
        self._shared_cast("leave", real, group)

    def _shared_cast(self, method: str, real: str, group: str) -> None:
        def one(p):
            self.rpc.cast(p, "shared", method, real, group, self.name,
                          key=real)

        for p in self.membership.peers():
            if self._repl_pool is not None:
                self._pool_submit(self._repl_pool, one, p)
            else:
                one(p)

    def shared_leader(self, real: str, group: str, msg=None) -> bool:
        """Pick the dispatching node for (real, group) per MESSAGE
        across the cluster-wide member-node set. Every member node holds
        the message already (route forwarding), so rotating the
        dispatcher balances the group across nodes with no extra RPC —
        the reference picks among cluster-wide members the same way
        (emqx_shared_sub.erl:234-285). Hash strategies stay keyed (same
        client/topic -> same node -> same member); sticky keeps a single
        dispatching node so the group genuinely sticks to one member.
        A local group not yet announced (race) defaults to dispatching —
        transient dup beats transient loss."""
        s = self._shared_nodes.get((real, group))
        if not s:
            return True
        # dispatch only asks when local members exist; the sorted
        # candidate list is cached per group (per-message sorting would
        # tax the hot path) and invalidated on membership changes
        cands = self._shared_cands.get((real, group))
        if cands is None:
            cands = sorted(set(s) | {self.name})
            self._shared_cands[(real, group)] = cands
        if len(cands) == 1:
            return True
        strategy = self.broker.shared.strategy
        if strategy == "sticky" or msg is None:
            return self.name == cands[0]
        if strategy == "hash_clientid":
            key = stable_hash(msg.from_client)
        elif strategy == "hash_topic":
            key = stable_hash(msg.topic)
        else:  # random / round_robin: rotate per message (mid is
            # GUID-stable across the forward path, so all member nodes
            # agree on the same dispatcher)
            key = stable_hash(f"{msg.from_client}|{msg.mid}")
        return self.name == cands[key % len(cands)]

    def _proto_shared_join(self, real: str, group: str, node: str) -> None:
        self._shared_nodes.setdefault((real, group), set()).add(node)
        self._shared_cands.pop((real, group), None)

    def _proto_shared_leave(self, real: str, group: str, node: str) -> None:
        s = self._shared_nodes.get((real, group))
        if s is not None:
            s.discard(node)
            if not s:
                self._shared_nodes.pop((real, group), None)
        self._shared_cands.pop((real, group), None)

    def _proto_shared_dump(self):
        return [
            (r, g, sorted(nodes))
            for (r, g), nodes in self._shared_nodes.items()
        ]

    def forward_batch_remote(self, msgs: Sequence[Message]) -> List[int]:
        """Forward already-locally-dispatched messages to their REMOTE
        route owners — the publish half the app's broker delegates here
        when cluster mode is on (local dispatch stays on the device batch
        path; this adds one forward_batch per destination node).
        Returns per-message remote destination counts.

        Batches carrying any QoS>0 message use a confirmed rpc.call
        (at-least-once, matching _dispatch_dests' per-message semantics);
        pure-QoS0 batches ride casts. In app mode the calls go through
        the replication worker so the event loop never blocks on a peer
        round-trip; failures count in messages.forward.failed."""
        all_dests = self.routes.match_dests_batch([m.topic for m in msgs])
        out = [0] * len(msgs)
        per_node: Dict[str, List[Tuple[Message, List[str]]]] = {}
        confirm: Dict[str, bool] = {}
        for i, (m, dests) in enumerate(zip(msgs, all_dests)):
            for node, filters in dests.items():
                # a dest whose owner died reroutes to the shard's
                # rendezvous successor; a successor that is US needs no
                # forward (local dispatch already ran on this batch)
                node = self._live_dest(node)
                if node == self.name:
                    continue
                per_node.setdefault(node, []).append((m, filters))
                if m.qos > 0:
                    confirm[node] = True
                out[i] += 1

        # span-context propagation is free — the `traceparent` header
        # rides inside the pickled Message — but the hop itself is worth
        # a span: record where each sampled trace LEFT this node
        rec = getattr(self.broker, "spans", None)
        if rec is not None:
            for node, batch in per_node.items():
                for m, _fs in batch:
                    rec.forward(m, node)

        def send(node, batch):
            if confirm.get(node) or self.forward_mode == "sync":
                try:
                    self.rpc.call(node, "broker", "forward_batch", batch)
                except RpcError:
                    self.broker.metrics.inc(
                        "messages.forward.failed", len(batch)
                    )
            else:
                self.rpc.cast(
                    node, "broker", "forward_batch", batch, key=node
                )

        for node, batch in per_node.items():
            if self._fwd_pool is not None:
                self._pool_submit(self._fwd_pool, send, node, batch)
            else:
                send(node, batch)
        return out

    def _dispatch_dests(self, msg: Message, dests: Dict[str, List[str]]) -> int:
        n = 0
        if not dests:
            self.broker.hooks.run("message.dropped", msg, "no_subscribers")
            return 0
        rec = getattr(self.broker, "spans", None)
        for node, filters in dests.items():  # aggre: one entry per node
            node = self._live_dest(node)
            if node == self.name:
                n += self.broker.dispatch(filters, msg)
            else:
                if rec is not None:
                    rec.forward(msg, node)
                if self.forward_mode == "sync" or msg.qos > 0:
                    try:
                        n += self.rpc.call(
                            node, "broker", "forward", msg, filters
                        )
                    except RpcError:
                        self.broker.metrics.inc("messages.forward.failed")
                else:
                    self.rpc.cast(
                        node, "broker", "forward", msg, filters, key=msg.topic
                    )
                    n += 1  # async: assumed delivered (gen_rpc cast)
        return n

    def _proto_forward(self, msg: Message, filters: List[str]) -> int:
        return self.broker.dispatch(filters, msg)

    def _proto_forward_batch(self, batch) -> int:
        """Inbound batched forward: ride the broker's device batch path
        (re-match + bitmap fan-out on the receiving node's own mirror,
        emqx_broker.erl:278-293 forward -> dispatch). Small batches fall
        through to the per-message host dispatch inside
        dispatch_batch_folded itself."""
        msgs = [m for m, _fs in batch]
        # forward=False: this IS the receiving half — re-forwarding here
        # would cascade batches between route owners forever
        # (same gate as the _handle marshal: a CLOSED loop must take the
        # sync path, or the reply would carry a never-awaited coroutine)
        if self._loop is not None and not self._loop.is_closed():
            # app mode: return a coroutine — the rpc marshal resolves the
            # reply when the dispatch ACTUALLY completes (QoS1 confirm =
            # delivered/banked) while any kernel launch/compile runs in
            # an executor thread, keeping the event loop free
            return self._afwd(msgs)
        return sum(self.broker.dispatch_batch_folded(msgs, forward=False))

    async def _afwd(self, msgs) -> int:
        res = await self.broker.adispatch_batch_folded(msgs, forward=False)
        return sum(res)

    # -- channel registry (emqx_cm_registry parity) ------------------------
    def register_channel(self, client_id: str, sid: str) -> None:
        with self._chan_lock:
            self._channels[client_id] = (self.name, sid)
        for p in self.membership.peers():
            self.rpc.cast(
                p, "cm", "insert_channel", client_id, self.name, sid,
                key=client_id,
            )

    def unregister_channel(self, client_id: str) -> None:
        with self._chan_lock:
            self._channels.pop(client_id, None)
        for p in self.membership.peers():
            self.rpc.cast(
                p, "cm", "delete_channel", client_id, self.name, key=client_id
            )

    def lookup_channel(self, client_id: str) -> Optional[Tuple[str, str]]:
        with self._chan_lock:
            v = self._channels.get(client_id)
        return tuple(v) if v else None

    def discard_session(self, client_id: str) -> bool:
        """Cluster-wide discard of an existing channel (emqx_cm.erl:245-273)."""
        found = self.lookup_channel(client_id)
        if not found:
            return False
        node, sid = found
        if node == self.name:
            return self._proto_discard(client_id)
        try:
            return self.rpc.call(node, "cm", "discard", client_id)
        except RpcError:
            return False

    def _proto_insert_channel(self, client_id: str, node: str, sid: str):
        with self._chan_lock:
            self._channels[client_id] = (node, sid)

    def _proto_delete_channel(self, client_id: str, node: str):
        with self._chan_lock:
            cur = self._channels.get(client_id)
            if cur and cur[0] == node:
                del self._channels[client_id]

    def _proto_discard(self, client_id: str) -> bool:
        """Drop the local channel's subscriptions + registry entry."""
        found = self.lookup_channel(client_id)
        if not found or found[0] != self.name:
            return False
        _, sid = found
        for cid, f, _ in list(self.broker.subscriptions()):
            if cid == client_id:
                self.unsubscribe(sid, f)
        self.unregister_channel(client_id)
        return True

    # -- persistent-session park/resume (emqx_session_router parity) -------
    def park_session(self, client_id: str, session_json: Dict, deadline: float) -> None:
        """Park a detached persistent session on this node: its wildcard/
        plain routes stay HERE (the separate persistent-session route
        table, emqx_session_router.erl), and matched messages bank in the
        park's pending list until a resume fetches them."""
        from emqx_tpu.mqtt import packet as pkt
        from emqx_tpu.storage.codec import msg_to_json, subopts_from_json

        park = {
            "session": session_json,
            "deadline": deadline,
            "pending": [],
            "marker": None,  # set by resume_begin: forward-to-node marker
        }
        self._parked[client_id] = park
        sid = f"parked:{client_id}"

        def deliver(msg: Message, opts: pkt.SubOpts) -> None:
            qos = min(msg.qos, opts.qos)
            if qos == 0:
                return
            with self._park_lock:
                park["pending"].append(msg_to_json(msg))

        for f, opts_json in session_json.get("subscriptions", {}).items():
            self.subscribe(sid, client_id, f, subopts_from_json(opts_json), deliver)
        self._parked_owner[client_id] = self.name
        for p in self.membership.peers():
            self.rpc.cast(p, "sess", "insert_parked", client_id, self.name)

    def resume_session(self, client_id: str, install=None):
        """Two-phase cross-node resume (emqx_session_router.erl:171-220
        resume_begin/resume_end with markers):

        1. resume_begin on the owner: returns the session snapshot + the
           pendings banked so far; the owner sets a marker and KEEPS
           routing, so messages arriving during the handoff keep banking.
        2. `install(session_json)` runs HERE, between the phases — the
           caller sets up its local routes for the session while the
           owner's park still catches in-flight traffic; only then
        3. resume_end on the owner returns the straggler pendings that
           arrived during the window and drops the park + its routes.

        Without an installed local route before resume_end, a message
        landing in the gap would match no route — the exact loss the
        marker protocol exists to prevent.

        Returns (session_json, pending_msgs) or None when no parked
        session exists anywhere.
        """
        owner = self._parked_owner.get(client_id)
        if owner is None:
            return None
        if owner == self.name:
            begin = self._proto_resume_begin(client_id, self.name)
        else:
            try:
                begin = self.rpc.call(
                    owner, "sess", "resume_begin", client_id, self.name
                )
            except RpcError:
                self._parked_owner.pop(client_id, None)
                return None
        if begin is None:
            return None
        snap, pending = begin
        if install is not None:
            install(snap)  # local routes live BEFORE the park is dropped
        if owner == self.name:
            stragglers = self._proto_resume_end(client_id)
        else:
            stragglers = self.rpc.call(owner, "sess", "resume_end", client_id)
        return snap, [
            self._msg_from(m) for m in list(pending) + list(stragglers)
        ]

    @staticmethod
    def _msg_from(m):
        from emqx_tpu.storage.codec import msg_from_json

        return msg_from_json(m)

    def _proto_insert_parked(self, client_id: str, node: str) -> None:
        self._parked_owner[client_id] = node

    def _proto_delete_parked(self, client_id: str) -> None:
        self._parked_owner.pop(client_id, None)

    def _proto_dump_parked(self) -> Dict[str, str]:
        return dict(self._parked_owner)

    def _proto_resume_begin(self, client_id: str, to_node: str):
        park = self._parked.get(client_id)
        if park is None:
            return None
        park["marker"] = to_node
        with self._park_lock:
            pending, park["pending"] = park["pending"], []
        return park["session"], pending

    def _proto_resume_end(self, client_id: str):
        park = self._parked.pop(client_id, None)
        if park is None:
            return []
        sid = f"parked:{client_id}"
        for f in park["session"].get("subscriptions", {}):
            self.unsubscribe(sid, f)
        self._parked_owner.pop(client_id, None)
        for p in self.membership.peers():
            self.rpc.cast(p, "sess", "delete_parked", client_id)
        return park["pending"]

    def _proto_park_remote(
        self, client_id: str, session_json: Dict, deadline: float
    ) -> bool:
        """Drain handoff phase 1 (sess v2): adopt a parked session from a
        draining peer. Routes go live HERE before the drainer drops its
        own, so an in-flight message lands in at least one bank."""
        self.park_session(client_id, session_json, deadline)
        return True

    def _proto_park_append(self, client_id: str, pendings) -> int:
        """Drain handoff phase 2: banked messages transferred AFTER the
        drainer's routes dropped (possible duplicates with phase-1 banking
        are QoS1 at-least-once, never loss)."""
        park = self._parked.get(client_id)
        if park is None:
            # the client resumed HERE between phase 1 and phase 2: its
            # session routes are live again — re-inject the backlog
            # through the normal publish path (dup-safe, never dropped)
            for m in pendings:
                self.publish(self._msg_from(m))
            return len(pendings)
        with self._park_lock:
            park["pending"].extend(pendings)
        return len(pendings)

    def _drain_one(self, peer: str, cid: str, rpc_call) -> bool:
        """Hand one parked session to `peer`; `rpc_call` performs the
        blocking calls (directly, or via an executor in app mode).

        Ordering: phase 1 makes the peer's park live (messages may now
        bank on BOTH sides — dups are at-least-once). Our routes then
        stay up while the bank drains in rounds, so a third node whose
        route table still lists us keeps landing messages in a bank that
        WILL be transferred; only once a sweep finds the bank empty do
        the local routes drop, and a final sweep ships any straggler
        that raced the drop. The residual window is a forward in flight
        after the final sweep — the same in-flight bound the resume
        marker protocol has (emqx_session_router.erl:171-220)."""
        park = self._parked.get(cid)
        if park is None:
            return False
        rpc_call(
            peer, "sess", "park_remote", cid, park["session"],
            park["deadline"],
        )
        while park["pending"]:
            with self._park_lock:
                batch, park["pending"] = park["pending"], []
            rpc_call(peer, "sess", "park_append", cid, batch)
        sid = f"parked:{cid}"
        for f in park["session"].get("subscriptions", {}):
            self.unsubscribe(sid, f)
        self._parked.pop(cid, None)
        if park["pending"]:  # raced the route drop: final sweep
            rpc_call(
                peer, "sess", "park_append", cid, list(park["pending"])
            )
        return True

    def drain_to(self, peer: str) -> int:
        """Rolling-upgrade drain (the relup analog, r3 verdict item 7;
        reference tooling: scripts/update_appup.escript — here the
        idiomatic equivalent is session handoff over the live protocol):
        every session parked on THIS node is re-parked on `peer` with the
        two-phase ordering above, then this node leaves the cluster.
        Returns the number of sessions handed off. The caller (node
        script / BrokerApp.drain) stops its listeners first so no new
        sessions appear mid-drain."""
        n = sum(
            self._drain_one(peer, cid, self.rpc.call)
            for cid in list(self._parked)
        )
        self.leave()
        return n

    async def drain_to_async(self, peer: str) -> int:
        """`drain_to` for app mode: the blocking rpc round-trips run in
        an executor so the event loop keeps serving inbound forwards —
        a message arriving mid-drain must still reach a bank (state
        mutations stay on the loop thread between the calls)."""
        import asyncio
        import functools

        loop = asyncio.get_running_loop()

        def rpc_sync(*a):
            return self.rpc.call(*a)

        n = 0
        for cid in list(self._parked):
            park = self._parked.get(cid)
            if park is None:
                continue
            await loop.run_in_executor(
                None,
                functools.partial(
                    rpc_sync, peer, "sess", "park_remote", cid,
                    park["session"], park["deadline"],
                ),
            )
            # drain the bank in rounds with routes still up (see
            # _drain_one's ordering comment), then drop + final sweep
            while park["pending"]:
                with self._park_lock:
                    batch, park["pending"] = park["pending"], []
                await loop.run_in_executor(
                    None,
                    functools.partial(
                        rpc_sync, peer, "sess", "park_append", cid, batch
                    ),
                )
            sid = f"parked:{cid}"
            for f in park["session"].get("subscriptions", {}):
                self.unsubscribe(sid, f)
            self._parked.pop(cid, None)
            if park["pending"]:
                await loop.run_in_executor(
                    None,
                    functools.partial(
                        rpc_sync, peer, "sess", "park_append", cid,
                        list(park["pending"]),
                    ),
                )
            n += 1
        await loop.run_in_executor(None, self.leave)
        return n

    # -- cluster config txn (emqx_cluster_rpc multicall parity) ------------
    def config_multicall(self, op: str, args: tuple) -> Dict[str, object]:
        """Append to the replicated config log and apply cluster-wide."""
        writer = min(self.membership.running_nodes())
        if writer == self.name:
            entry = self.conf_log.append(op, args)
        else:
            entry = tuple(self.rpc.call(writer, "conf", "append", op, args))
            self.conf_log.receive(entry)
        results: Dict[str, object] = {self.name: self.conf_log.apply_pending()}
        for p in self.membership.peers():
            try:
                results[p] = self.rpc.call(p, "conf", "receive_apply", entry)
            except RpcError as e:
                results[p] = ("badrpc", str(e))
        return results

    def _proto_conf_receive_apply(self, entry) -> int:
        self.conf_log.receive(tuple(entry))
        return self.conf_log.apply_pending()

    # -- introspection -----------------------------------------------------
    def stats(self) -> Dict[str, object]:
        s = dict(self.routes.stats())
        s["node"] = self.name
        s["peers"] = self.membership.peers()
        s["channels.count"] = len(self._channels)
        return s

    def flush(self) -> None:
        """Drain async forwards/replication (test determinism)."""
        self.rpc.flush()


def make_cluster(
    n: int,
    clock: Optional[Callable[[], float]] = None,
    forward_mode: str = "async",
) -> Tuple[LocalBus, List[ClusterNode]]:
    """n-node in-process cluster, fully joined."""
    bus = LocalBus()
    nodes = [
        ClusterNode(f"node{i}@cluster", bus, clock=clock, forward_mode=forward_mode)
        for i in range(n)
    ]
    for node in nodes[1:]:
        node.join(nodes[0].name)
    return bus, nodes
