"""ClusterNode: one broker node wired into the cluster fabric.

Composes the local pub/sub kernel (`Broker`) with:
- membership (ekka parity) with route GC on nodedown
  (emqx_router_helper.erl:96,135-148),
- the replicated route table (mria parity),
- BPAPI-versioned RPC protos: broker-forward, route replication, channel
  registry, cluster config log — mirroring the reference's four proto
  families (apps/emqx/src/proto/: broker, cm, persistent_session, emqx),
- cross-node publish forwarding with per-node aggre dedup
  (emqx_broker.erl:262-293): ONE forward per (message, node) carrying the
  matched filters so the owner node skips re-matching,
- cluster-wide clientid→node channel registry (emqx_cm_registry parity),
- replicated config transaction log (emqx_cluster_rpc parity).

`make_cluster(n)` builds an n-node in-process cluster on a LocalBus — the
analog of the reference's slave-node CT harness
(emqx_router_helper_SUITE.erl:61, emqx_cluster_rpc_SUITE.erl:25-27).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.message import Message
from emqx_tpu.cluster.cluster_rpc import ClusterRpcLog
from emqx_tpu.cluster.membership import Membership
from emqx_tpu.cluster.route_sync import ClusterRouteTable
from emqx_tpu.cluster.rpc import Rpc, RpcError
from emqx_tpu.cluster.transport import LocalBus
from emqx_tpu.mqtt import packet as pkt
from emqx_tpu.ops import topics as T


class ClusterNode:
    def __init__(
        self,
        name: str,
        bus: LocalBus,
        clock: Optional[Callable[[], float]] = None,
        broker: Optional[Broker] = None,
        forward_mode: str = "async",
    ) -> None:
        self.name = name
        self.bus = bus
        self.broker = broker or Broker()
        self.routes = ClusterRouteTable(name)
        self.membership = Membership(name, bus, clock=clock)
        self.rpc = Rpc(name, bus)
        self.conf_log = ClusterRpcLog(name)
        self.forward_mode = forward_mode
        self._chan_lock = threading.Lock()
        # clientid -> (node, sid): replicated channel registry
        self._channels: Dict[str, Tuple[str, str]] = {}
        # persistent-session router state (emqx_session_router parity):
        # locally parked sessions + the replicated clientid -> owner map
        self._parked: Dict[str, Dict] = {}
        self._parked_owner: Dict[str, str] = {}
        self._register_protos()
        self.membership.monitor(self._on_membership)
        bus.attach(name, self._handle)

    # -- wiring ------------------------------------------------------------
    def _handle(self, from_node: str, payload):
        kind = payload[0]
        if kind == "membership":
            return self.membership.handle(from_node, payload)
        if kind == "rpc":
            return self.rpc.handle(from_node, payload)
        return None

    def _register_protos(self) -> None:
        self.rpc.registry.register(
            "broker",
            1,
            {
                "forward": self._proto_forward,
                "forward_batch": self._proto_forward_batch,
            },
        )
        self.rpc.registry.register(
            "route",
            1,
            {
                "add_route": self.routes.add_route,
                "delete_route": self.routes.delete_route,
                "dump": self.routes.dump,
            },
        )
        self.rpc.registry.register(
            "cm",
            1,
            {
                "insert_channel": self._proto_insert_channel,
                "delete_channel": self._proto_delete_channel,
                "lookup_channel": self.lookup_channel,
                "discard": self._proto_discard,
            },
        )
        self.rpc.registry.register(
            "conf",
            1,
            {
                "append": self.conf_log.append,
                "receive_apply": self._proto_conf_receive_apply,
                "entries_after": self.conf_log.entries_after,
            },
        )
        self.rpc.registry.register(
            "sess",
            1,
            {
                "insert_parked": self._proto_insert_parked,
                "delete_parked": self._proto_delete_parked,
                "resume_begin": self._proto_resume_begin,
                "resume_end": self._proto_resume_end,
                "dump_parked": self._proto_dump_parked,
            },
        )

    def _on_membership(self, event: str, node: str) -> None:
        if event == "node_down":
            # sessions parked on a dead node are unreachable until it
            # returns: purge the owner entries so reconnecting clients get
            # fresh sessions instead of resume limbo (route-GC semantics)
            gone = [
                cid for cid, n in self._parked_owner.items() if n == node
            ]
            for cid in gone:
                self._parked_owner.pop(cid, None)
            purged = self.routes.cleanup_node(node)
            with self._chan_lock:
                for cid, (n, _) in list(self._channels.items()):
                    if n == node:
                        del self._channels[cid]
            self.rpc.forget_peer(node)
            self.broker.metrics.inc("cluster.nodedown.routes_purged", purged)
        elif event == "node_up":
            self.rpc.forget_peer(node)  # re-negotiate BPAPI versions

    # -- lifecycle ---------------------------------------------------------
    def join(self, seed: str) -> bool:
        """Join the cluster: membership, route bootstrap, conf catch-up."""
        if not self.membership.join(seed):
            return False
        # pull the seed's route replica (mria replicant catch-up)
        self.routes.load(self.rpc.call(seed, "route", "dump"))
        # push our own local routes to everyone
        mine = [(f, ns) for f, ns in self.routes.dump() if self.name in ns]
        for peer in self.membership.peers():
            for f, _ in mine:
                self.rpc.cast(peer, "route", "add_route", f, self.name, key=f)
        # config log catch-up
        entries = self.rpc.call(seed, "conf", "entries_after", self.conf_log.cursor)
        self.conf_log.catch_up_from([tuple(e) for e in entries])
        # parked-session owner map bootstrap (a late joiner must be able
        # to resume sessions parked before it joined)
        self._parked_owner.update(
            self.rpc.call(seed, "sess", "dump_parked")
        )
        return True

    def leave(self) -> None:
        self.membership.leave()
        self.rpc.stop()
        self.bus.detach(self.name)

    # -- subscribe side ----------------------------------------------------
    def subscribe(
        self,
        sid: str,
        client_id: str,
        filter_: str,
        opts: pkt.SubOpts,
        deliver,
    ) -> None:
        group, real = T.parse_share(filter_)
        route_key = (
            self.broker.shared.route_filter(group, real)
            if group is not None
            else real
        )
        first = not self.broker.has_local_subs(route_key)
        self.broker.subscribe(sid, client_id, filter_, opts, deliver)
        if first:
            self._replicate_add(route_key)

    def unsubscribe(self, sid: str, filter_: str) -> bool:
        group, real = T.parse_share(filter_)
        route_key = (
            self.broker.shared.route_filter(group, real)
            if group is not None
            else real
        )
        removed = self.broker.unsubscribe(sid, filter_)
        if removed and not self.broker.has_local_subs(route_key):
            self._replicate_delete(route_key)
        return removed

    def _replicate_add(self, filter_: str) -> None:
        self.routes.add_route(filter_, self.name)
        peers = self.membership.peers()
        if T.wildcard(filter_):
            # transactional: wait for every reachable peer (maybe_trans,
            # emqx_router.erl:118-121 — a torn trie edge breaks matching)
            for p in peers:
                try:
                    self.rpc.call(p, "route", "add_route", filter_, self.name)
                except RpcError:
                    pass  # peer down: membership GC will reconcile
        else:
            for p in peers:
                self.rpc.cast(
                    p, "route", "add_route", filter_, self.name, key=filter_
                )

    def _replicate_delete(self, filter_: str) -> None:
        self.routes.delete_route(filter_, self.name)
        for p in self.membership.peers():
            if T.wildcard(filter_):
                try:
                    self.rpc.call(
                        p, "route", "delete_route", filter_, self.name
                    )
                except RpcError:
                    pass
            else:
                self.rpc.cast(
                    p, "route", "delete_route", filter_, self.name, key=filter_
                )

    # -- publish side ------------------------------------------------------
    def publish(self, msg: Message) -> int:
        """Cluster publish: match once, dispatch local, forward per node."""
        msg = self.broker.hooks.run_fold("message.publish", (), msg)
        if msg is None or msg.headers.get("allow_publish") is False:
            self.broker.metrics.inc("messages.dropped")
            return 0
        dests = self.routes.match_dests(msg.topic)
        return self._dispatch_dests(msg, dests)

    def publish_batch(self, msgs: Sequence[Message]) -> int:
        """One route-table match kernel for the whole batch, then fan out.

        Remote fan-out is batched per destination node: a single
        forward_batch per (batch, node) instead of per (message, node) —
        the batching the TPU design adds over the reference hot path.
        """
        kept: List[Message] = []
        for m in msgs:
            m = self.broker.hooks.run_fold("message.publish", (), m)
            if m is not None and m.headers.get("allow_publish") is not False:
                kept.append(m)
        all_dests = self.routes.match_dests_batch([m.topic for m in kept])
        total = 0
        per_node: Dict[str, List[Tuple[Message, List[str]]]] = {}
        for m, dests in zip(kept, all_dests):
            for node, filters in dests.items():
                if node == self.name:
                    total += self.broker.dispatch(filters, m)
                else:
                    per_node.setdefault(node, []).append((m, filters))
        for node, batch in per_node.items():
            self.rpc.cast(node, "broker", "forward_batch", batch, key=node)
            total += sum(1 for _ in batch)
        return total

    def _dispatch_dests(self, msg: Message, dests: Dict[str, List[str]]) -> int:
        n = 0
        if not dests:
            self.broker.hooks.run("message.dropped", msg, "no_subscribers")
            return 0
        for node, filters in dests.items():  # aggre: one entry per node
            if node == self.name:
                n += self.broker.dispatch(filters, msg)
            else:
                if self.forward_mode == "sync" or msg.qos > 0:
                    try:
                        n += self.rpc.call(
                            node, "broker", "forward", msg, filters
                        )
                    except RpcError:
                        self.broker.metrics.inc("messages.forward.failed")
                else:
                    self.rpc.cast(
                        node, "broker", "forward", msg, filters, key=msg.topic
                    )
                    n += 1  # async: assumed delivered (gen_rpc cast)
        return n

    def _proto_forward(self, msg: Message, filters: List[str]) -> int:
        return self.broker.dispatch(filters, msg)

    def _proto_forward_batch(self, batch) -> int:
        """Inbound batched forward: ride the broker's device batch path
        (re-match + bitmap fan-out on the receiving node's own mirror,
        emqx_broker.erl:278-293 forward -> dispatch). Small batches fall
        through to the per-message host dispatch inside
        dispatch_batch_folded itself."""
        msgs = [m for m, _fs in batch]
        return sum(self.broker.dispatch_batch_folded(msgs))

    # -- channel registry (emqx_cm_registry parity) ------------------------
    def register_channel(self, client_id: str, sid: str) -> None:
        with self._chan_lock:
            self._channels[client_id] = (self.name, sid)
        for p in self.membership.peers():
            self.rpc.cast(
                p, "cm", "insert_channel", client_id, self.name, sid,
                key=client_id,
            )

    def unregister_channel(self, client_id: str) -> None:
        with self._chan_lock:
            self._channels.pop(client_id, None)
        for p in self.membership.peers():
            self.rpc.cast(
                p, "cm", "delete_channel", client_id, self.name, key=client_id
            )

    def lookup_channel(self, client_id: str) -> Optional[Tuple[str, str]]:
        with self._chan_lock:
            v = self._channels.get(client_id)
        return tuple(v) if v else None

    def discard_session(self, client_id: str) -> bool:
        """Cluster-wide discard of an existing channel (emqx_cm.erl:245-273)."""
        found = self.lookup_channel(client_id)
        if not found:
            return False
        node, sid = found
        if node == self.name:
            return self._proto_discard(client_id)
        try:
            return self.rpc.call(node, "cm", "discard", client_id)
        except RpcError:
            return False

    def _proto_insert_channel(self, client_id: str, node: str, sid: str):
        with self._chan_lock:
            self._channels[client_id] = (node, sid)

    def _proto_delete_channel(self, client_id: str, node: str):
        with self._chan_lock:
            cur = self._channels.get(client_id)
            if cur and cur[0] == node:
                del self._channels[client_id]

    def _proto_discard(self, client_id: str) -> bool:
        """Drop the local channel's subscriptions + registry entry."""
        found = self.lookup_channel(client_id)
        if not found or found[0] != self.name:
            return False
        _, sid = found
        for cid, f, _ in list(self.broker.subscriptions()):
            if cid == client_id:
                self.unsubscribe(sid, f)
        self.unregister_channel(client_id)
        return True

    # -- persistent-session park/resume (emqx_session_router parity) -------
    def park_session(self, client_id: str, session_json: Dict, deadline: float) -> None:
        """Park a detached persistent session on this node: its wildcard/
        plain routes stay HERE (the separate persistent-session route
        table, emqx_session_router.erl), and matched messages bank in the
        park's pending list until a resume fetches them."""
        from emqx_tpu.mqtt import packet as pkt
        from emqx_tpu.storage.codec import msg_to_json, subopts_from_json

        park = {
            "session": session_json,
            "deadline": deadline,
            "pending": [],
            "marker": None,  # set by resume_begin: forward-to-node marker
        }
        self._parked[client_id] = park
        sid = f"parked:{client_id}"

        def deliver(msg: Message, opts: pkt.SubOpts) -> None:
            qos = min(msg.qos, opts.qos)
            if qos == 0:
                return
            park["pending"].append(msg_to_json(msg))

        for f, opts_json in session_json.get("subscriptions", {}).items():
            self.subscribe(sid, client_id, f, subopts_from_json(opts_json), deliver)
        self._parked_owner[client_id] = self.name
        for p in self.membership.peers():
            self.rpc.cast(p, "sess", "insert_parked", client_id, self.name)

    def resume_session(self, client_id: str, install=None):
        """Two-phase cross-node resume (emqx_session_router.erl:171-220
        resume_begin/resume_end with markers):

        1. resume_begin on the owner: returns the session snapshot + the
           pendings banked so far; the owner sets a marker and KEEPS
           routing, so messages arriving during the handoff keep banking.
        2. `install(session_json)` runs HERE, between the phases — the
           caller sets up its local routes for the session while the
           owner's park still catches in-flight traffic; only then
        3. resume_end on the owner returns the straggler pendings that
           arrived during the window and drops the park + its routes.

        Without an installed local route before resume_end, a message
        landing in the gap would match no route — the exact loss the
        marker protocol exists to prevent.

        Returns (session_json, pending_msgs) or None when no parked
        session exists anywhere.
        """
        owner = self._parked_owner.get(client_id)
        if owner is None:
            return None
        if owner == self.name:
            begin = self._proto_resume_begin(client_id, self.name)
        else:
            try:
                begin = self.rpc.call(
                    owner, "sess", "resume_begin", client_id, self.name
                )
            except RpcError:
                self._parked_owner.pop(client_id, None)
                return None
        if begin is None:
            return None
        snap, pending = begin
        if install is not None:
            install(snap)  # local routes live BEFORE the park is dropped
        if owner == self.name:
            stragglers = self._proto_resume_end(client_id)
        else:
            stragglers = self.rpc.call(owner, "sess", "resume_end", client_id)
        return snap, [
            self._msg_from(m) for m in list(pending) + list(stragglers)
        ]

    @staticmethod
    def _msg_from(m):
        from emqx_tpu.storage.codec import msg_from_json

        return msg_from_json(m)

    def _proto_insert_parked(self, client_id: str, node: str) -> None:
        self._parked_owner[client_id] = node

    def _proto_delete_parked(self, client_id: str) -> None:
        self._parked_owner.pop(client_id, None)

    def _proto_dump_parked(self) -> Dict[str, str]:
        return dict(self._parked_owner)

    def _proto_resume_begin(self, client_id: str, to_node: str):
        park = self._parked.get(client_id)
        if park is None:
            return None
        park["marker"] = to_node
        pending, park["pending"] = park["pending"], []
        return park["session"], pending

    def _proto_resume_end(self, client_id: str):
        park = self._parked.pop(client_id, None)
        if park is None:
            return []
        sid = f"parked:{client_id}"
        for f in park["session"].get("subscriptions", {}):
            self.unsubscribe(sid, f)
        self._parked_owner.pop(client_id, None)
        for p in self.membership.peers():
            self.rpc.cast(p, "sess", "delete_parked", client_id)
        return park["pending"]

    # -- cluster config txn (emqx_cluster_rpc multicall parity) ------------
    def config_multicall(self, op: str, args: tuple) -> Dict[str, object]:
        """Append to the replicated config log and apply cluster-wide."""
        writer = min(self.membership.running_nodes())
        if writer == self.name:
            entry = self.conf_log.append(op, args)
        else:
            entry = tuple(self.rpc.call(writer, "conf", "append", op, args))
            self.conf_log.receive(entry)
        results: Dict[str, object] = {self.name: self.conf_log.apply_pending()}
        for p in self.membership.peers():
            try:
                results[p] = self.rpc.call(p, "conf", "receive_apply", entry)
            except RpcError as e:
                results[p] = ("badrpc", str(e))
        return results

    def _proto_conf_receive_apply(self, entry) -> int:
        self.conf_log.receive(tuple(entry))
        return self.conf_log.apply_pending()

    # -- introspection -----------------------------------------------------
    def stats(self) -> Dict[str, object]:
        s = dict(self.routes.stats())
        s["node"] = self.name
        s["peers"] = self.membership.peers()
        s["channels.count"] = len(self._channels)
        return s

    def flush(self) -> None:
        """Drain async forwards/replication (test determinism)."""
        self.rpc.flush()


def make_cluster(
    n: int,
    clock: Optional[Callable[[], float]] = None,
    forward_mode: str = "async",
) -> Tuple[LocalBus, List[ClusterNode]]:
    """n-node in-process cluster, fully joined."""
    bus = LocalBus()
    nodes = [
        ClusterNode(f"node{i}@cluster", bus, clock=clock, forward_mode=forward_mode)
        for i in range(n)
    ]
    for node in nodes[1:]:
        node.join(nodes[0].name)
    return bus, nodes
