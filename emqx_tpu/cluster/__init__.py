"""Cluster layer: membership, RPC, replicated routes, cross-node forwarding.

The reference's three communication planes (SURVEY.md §5.8):
  (i)  control/membership — ekka on distributed Erlang
  (ii) state replication  — mria (mnesia + async rlog shards)
  (iii) data plane        — gen_rpc multi-channel TCP, keyed ordered channels

This package reproduces each plane TPU-host-side:
  (i)  `membership.Membership`  — cluster view + nodedown callbacks
  (ii) `route_sync.ClusterRouteTable` — replicated topic→nodes table with
       dirty (async) plain-route writes and transactional wildcard writes
  (iii) `rpc.Rpc` over `transport.LocalBus` — keyed channels preserving
       per-topic ordering, sync call / async cast, BPAPI-versioned protos

Multi-chip TPU state (the NFA tables) is *replicated* per node like the
reference replicates its trie to every core node; subscriber bitmaps stay
node-local, exactly as ETS subscriber tables do.
"""

from emqx_tpu.cluster.membership import Membership
from emqx_tpu.cluster.node import ClusterNode, make_cluster
from emqx_tpu.cluster.rpc import Rpc, RpcError
from emqx_tpu.cluster.transport import LocalBus

__all__ = [
    "Membership",
    "ClusterNode",
    "make_cluster",
    "Rpc",
    "RpcError",
    "LocalBus",
]
