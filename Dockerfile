# emqx_tpu broker image (deploy/docker analog of the reference).
# CPU JAX by default; swap the jax install for jax[tpu] on TPU hosts.
FROM python:3.12-slim

WORKDIR /opt/emqx_tpu
COPY pyproject.toml README.md ./
COPY emqx_tpu ./emqx_tpu
RUN pip install --no-cache-dir .

# MQTT, WebSocket upgrade via the same TCP port set, mgmt API
EXPOSE 1883 8083 8883 18083

# config mounted at /opt/emqx_tpu/etc/emqx_tpu.json (EMQX_TPU__* env
# overrides also apply, bin/emqx HOCON_ENV_OVERRIDE_PREFIX analog)
VOLUME ["/opt/emqx_tpu/etc", "/opt/emqx_tpu/data"]

ENTRYPOINT ["emqx-tpu"]
CMD ["-c", "/opt/emqx_tpu/etc/emqx_tpu.json"]
