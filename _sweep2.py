import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np, jax, jax.numpy as jnp
from emqx_tpu.models.router_model import shape_route_step
from emqx_tpu.ops.route_index import RouteIndex
from emqx_tpu.ops.tokenizer import encode_topics

idx = RouteIndex()
for i in range(211):
    idx.add(f"site/{i}/dev/+/ch/#")
st = {k: jax.device_put(v.copy()) for k, v in idx.shapes.device_snapshot().items()}
m_active = idx.shapes.m_active(floor=1)
B = 1<<20
topics = [f"site/{i % 211}/dev/{i % 7919}/ch/{i}" for i in range(B)]
mat, lens, _ = encode_topics(topics, 64)
bm, ln = jax.device_put(mat), jax.device_put(lens)

def launch():
    return shape_route_step(st, None, None, bm, ln, m_active=m_active,
                            with_nfa=False, salt=idx.salt, max_levels=8)
r = launch(); jax.block_until_ready(r["matched"])

def t_launches(tag, n=3):
    t=time.perf_counter()
    for _ in range(n): r = launch()
    jax.block_until_ready(r["matched"])
    print(f"{tag}: {(time.perf_counter()-t)/n*1e3:.1f} ms/launch", flush=True)

t_launches("before any readback")
x = np.asarray(launch()["matched"])   # one full readback (4MB)
print("did readback of", x.nbytes/1e6, "MB")
t_launches("after 1 readback")
for _ in range(5):
    x = np.asarray(launch()["matched"])
t_launches("after 6 readbacks")
t_launches("again (stable?)")
